"""Welfare telemetry plane end-to-end (ISSUE 16 tentpole layer 2).

Acceptance proofs pinned here:

* **Fleet federation is exact**: on a live 3-replica fake fleet, the
  ``replica="fleet"`` latency sketch in the federated snapshot equals the
  key-wise merge of the per-replica series — same stores, same p99 — and
  its exemplars carry trace ids resolvable via ``GET /v1/trace/<id>``.
* **Telemetry OFF is inert**: the same seeded requests produce identical
  response bodies (modulo the wall-clock ``generation_time_s``) with
  telemetry on and off, and an OFF registry grows no sketch families.
* **Drift detection**: the ``welfare_drift`` condition stays silent on a
  stationary reference workload, flags a median collapse AND a
  p10-only skew (the worst-off tail moving while the median holds), and
  ``welfare_drift_events_total`` counts each raise transition once.
* **Tier accounting**: degraded responses are attributed to their tier
  and ``serve_degraded_welfare_gap`` tracks full-minus-tier egalitarian
  welfare.
* **The score-matrix sink**: ``record_matrix`` feeds the chosen row's
  welfare and worst-off utility; the module-level sink installs and
  clears.
"""

import json
import urllib.request

import numpy as np
import pytest

from consensus_tpu.obs.metrics import Registry
from consensus_tpu.obs.sketch import merge_sketch_series, quantile_from_series
from consensus_tpu.obs.welfare import (
    ServeTelemetry,
    WelfareDriftDetector,
    get_welfare_sink,
    set_welfare_sink,
)
from consensus_tpu.serve import create_server

ISSUE = "Should we invest in public transport?"
OPINIONS = {
    "Agent 1": "Yes, buses and trains are vital public goods.",
    "Agent 2": "Only alongside congestion pricing for cars.",
    "Agent 3": "Prefer cycling infrastructure over big rail projects.",
}


@pytest.fixture(autouse=True)
def _clear_sink():
    yield
    set_welfare_sink(None)


def _payload(seed=7, issue=ISSUE, **overrides):
    payload = {
        "issue": issue,
        "agent_opinions": dict(OPINIONS),
        "method": "best_of_n",
        "params": {"n": 4, "max_tokens": 24},
        "seed": seed,
        "evaluate": True,
        "request_id": f"req-{seed}",
    }
    payload.update(overrides)
    return payload


def _post(base_url, payload, timeout=30.0):
    request = urllib.request.Request(
        base_url + "/v1/consensus",
        data=json.dumps(payload).encode("utf-8"),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(request, timeout=timeout) as response:
        return response.status, json.loads(response.read().decode())


def _get(base_url, path, timeout=10.0):
    with urllib.request.urlopen(base_url + path, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read().decode())


def _series(registry, family, **labels):
    fam = registry.snapshot()["families"].get(family)
    if fam is None:
        return None
    for series in fam["series"]:
        if all(series["labels"].get(k) == v for k, v in labels.items()):
            return series
    return None


# ---------------------------------------------------------------------------
# Drift detector
# ---------------------------------------------------------------------------


class TestWelfareDriftDetector:
    def test_warming_up_until_min_samples(self):
        det = WelfareDriftDetector(window=64, min_samples=32)
        for _ in range(10):
            det.observe(0.5)
        status = det.status()
        assert status["reason"] == "warming_up"
        assert status["drifted"] is False

    def test_silent_on_stationary_reference(self):
        det = WelfareDriftDetector(window=64, min_samples=32)
        pattern = [0.45, 0.5, 0.55, 0.5]
        for i in range(200):
            det.observe(pattern[i % 4])
            assert det.status()["drifted"] is False

    def test_flags_median_collapse(self):
        det = WelfareDriftDetector(window=64, min_samples=32)
        for _ in range(64):
            det.observe(0.5)  # baseline auto-pins at sample 32
        for _ in range(64):
            det.observe(0.1)  # workload shifts
        status = det.status()
        assert status["drifted"] is True
        assert status["shift"]["median"] > 0.25
        assert status["baseline"]["median"] == pytest.approx(0.5, rel=0.02)
        assert status["window"]["median"] == pytest.approx(0.1, rel=0.02)

    def test_flags_p10_only_skew(self):
        # The median holds at 0.5 while 15% of requests collapse to 0.1:
        # exactly the shift that hurts the worst-off agents.  The p10 term
        # must catch it.
        det = WelfareDriftDetector(window=40, min_samples=20)
        for _ in range(20):
            det.observe(0.5)
        for i in range(40):
            det.observe(0.1 if i % 7 == 0 else 0.5)
        status = det.status()
        assert status["window"]["median"] == pytest.approx(0.5, rel=0.02)
        assert status["shift"]["median"] < 0.05
        assert status["shift"]["p10"] > 0.25
        assert status["drifted"] is True

    def test_pin_baseline_from_saved_snapshot(self):
        reference = WelfareDriftDetector(window=64, min_samples=8)
        for _ in range(16):
            reference.observe(0.8)
        saved = reference.baseline_snapshot()
        assert saved is not None

        det = WelfareDriftDetector(window=64, min_samples=8)
        det.pin_baseline(saved)
        for _ in range(8):
            det.observe(0.79)  # near the shipped baseline: no drift
        assert det.status()["drifted"] is False
        for _ in range(64):
            det.observe(0.2)
        assert det.status()["drifted"] is True

    def test_rejects_degenerate_window(self):
        with pytest.raises(ValueError):
            WelfareDriftDetector(window=1)
        with pytest.raises(ValueError):
            WelfareDriftDetector(min_samples=1)


# ---------------------------------------------------------------------------
# ServeTelemetry unit behavior (no server)
# ---------------------------------------------------------------------------


def _evaluated_value(egal=0.3, util=0.5, nash=0.4, worst=0.2, **extra):
    value = {
        "welfare": {
            "egalitarian_welfare_cosine": egal,
            "utilitarian_welfare_cosine": util,
            "log_nash_welfare_cosine": nash,
        },
        "utilities": {
            "a": {"cosine_similarity": worst},
            "b": {"cosine_similarity": 0.8},
        },
    }
    value.update(extra)
    return value


class TestServeTelemetry:
    def test_record_request_feeds_sketches_and_gap(self):
        registry = Registry()
        telemetry = ServeTelemetry(registry=registry)
        telemetry.record_request(
            "best_of_n", "ok", latency_s=0.25,
            value=_evaluated_value(), replica="r0", trace_id="req-1",
        )
        latency = _series(registry, "serve_latency_sketch_seconds",
                          replica="r0", outcome="ok")
        assert latency["count"] == 1
        assert latency["exemplars"][0]["trace_id"] == "req-1"
        assert _series(registry, "welfare_egalitarian",
                       replica="r0")["count"] == 1
        assert _series(registry, "min_agent_utility",
                       replica="r0")["sum"] == pytest.approx(0.2)
        gap = _series(registry, "welfare_gap_util_egal", replica="r0")
        assert gap["value"] == pytest.approx(0.5 - 0.3)

    def test_unevaluated_request_records_latency_only(self):
        registry = Registry()
        telemetry = ServeTelemetry(registry=registry)
        telemetry.record_request("best_of_n", "ok", latency_s=0.1,
                                 value={"statement": "s"}, replica="r0")
        assert _series(registry, "serve_latency_sketch_seconds",
                       replica="r0", outcome="ok")["count"] == 1
        assert _series(registry, "welfare_egalitarian",
                       replica="r0") is None

    def test_garbage_value_never_raises(self):
        telemetry = ServeTelemetry(registry=Registry())
        telemetry.record_request("m", "ok", latency_s=0.1, value="not a dict")
        telemetry.record_request(
            "m", "ok", latency_s=0.1,
            value={"welfare": {"egalitarian_welfare_cosine": "NaNsense"},
                   "utilities": {"a": {}}},
        )
        telemetry.record_request("m", "failed", latency_s=float("nan"))

    def test_degraded_tier_gap_accounting(self):
        registry = Registry()
        telemetry = ServeTelemetry(registry=registry)
        for _ in range(2):
            telemetry.record_request(
                "m", "ok", 0.1, value=_evaluated_value(egal=0.6))
        telemetry.record_request(
            "m", "degraded", 0.1,
            value=_evaluated_value(egal=0.2, degraded=True),
            tier="brownout2",
        )
        gap = _series(registry, "serve_degraded_welfare_gap",
                      tier="brownout2")
        assert gap["value"] == pytest.approx(0.4)
        assert _series(registry, "welfare_by_tier",
                       tier="full")["count"] == 2
        assert _series(registry, "welfare_by_tier",
                       tier="brownout2")["count"] == 1
        snap = telemetry.snapshot()
        assert snap["tiers"]["full"]["mean"] == pytest.approx(0.6)
        assert snap["tiers"]["brownout2"]["mean"] == pytest.approx(0.2)

    def test_degraded_reason_fallback_when_tier_unset(self):
        registry = Registry()
        telemetry = ServeTelemetry(registry=registry)
        telemetry.record_request(
            "m", "degraded", 0.1,
            value=_evaluated_value(egal=0.2, degraded=True,
                                   degraded_reason="anytime_partial"),
        )
        assert _series(registry, "welfare_by_tier",
                       tier="anytime_partial")["count"] == 1

    def test_drift_event_counter_increments_once_per_raise(self):
        registry = Registry()
        telemetry = ServeTelemetry(
            registry=registry,
            drift_options={"window": 32, "min_samples": 8},
        )
        for _ in range(8):
            telemetry.record_request(
                "m", "ok", 0.1, value=_evaluated_value(egal=0.5))
        for _ in range(40):
            telemetry.record_request(
                "m", "ok", 0.1, value=_evaluated_value(egal=0.1))
        assert _series(registry, "welfare_drift")["value"] == 1.0
        # Raised once, not once per drifted observation.
        assert _series(registry, "welfare_drift_events_total")["value"] == 1
        assert telemetry.drift_status()["drifted"] is True

    def test_record_matrix_feeds_score_path(self):
        registry = Registry()
        telemetry = ServeTelemetry(registry=registry)

        class FakeResult:
            welfare = np.array([0.2, 0.7, 0.4])
            best = 1
            utilities = np.array([[0.1, 0.3], [0.6, 0.9], [0.2, 0.5]])

        telemetry.record_matrix(FakeResult(), welfare_rule="egalitarian")
        chosen = _series(registry, "score_path_welfare", rule="egalitarian")
        assert chosen["count"] == 1 and chosen["sum"] == pytest.approx(0.7)
        worst = _series(registry, "score_path_min_agent_utility")
        assert worst["sum"] == pytest.approx(0.6)
        # Malformed results never raise.
        telemetry.record_matrix(object())

    def test_sink_installs_and_clears(self):
        telemetry = ServeTelemetry(registry=Registry())
        assert get_welfare_sink() is None
        assert set_welfare_sink(telemetry) is telemetry
        assert get_welfare_sink() is telemetry
        set_welfare_sink(None)
        assert get_welfare_sink() is None


# ---------------------------------------------------------------------------
# End-to-end: byte-identity with telemetry off
# ---------------------------------------------------------------------------


def _serve_bodies(telemetry, registry, seeds=(7, 8, 9)):
    server = create_server(
        backend="fake", port=0, registry=registry, max_inflight=4,
        telemetry=telemetry, slo=telemetry,
    ).start()
    try:
        bodies = []
        for seed in seeds:
            status, body = _post(server.base_url, _payload(seed=seed))
            assert status == 200
            # The only wall-clock field in a response.
            body.pop("generation_time_s")
            bodies.append(json.dumps(body, sort_keys=True))
        return bodies
    finally:
        server.stop(drain=False, timeout=5.0)
        set_welfare_sink(None)


class TestTelemetryOffIdentity:
    def test_responses_identical_on_vs_off(self):
        on = _serve_bodies(True, Registry())
        off = _serve_bodies(False, Registry())
        assert on == off

    def test_off_registry_grows_no_telemetry_families(self):
        registry = Registry()
        _serve_bodies(False, registry)
        families = registry.snapshot()["families"]
        assert "serve_latency_sketch_seconds" not in families
        assert not any(name.startswith("welfare") for name in families)
        assert "slo_state" not in families

    def test_off_surfaces_absent(self):
        registry = Registry()
        server = create_server(
            backend="fake", port=0, registry=registry, max_inflight=4,
        ).start()
        try:
            status, health = _get(server.base_url, "/healthz")
            assert "welfare" not in health and "slo" not in health
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(server.base_url, "/v1/slo")
            assert err.value.code == 404
        finally:
            server.stop(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# End-to-end: fleet federation + exemplar linkage + live surfaces
# ---------------------------------------------------------------------------


class TestFleetFederation:
    def test_fleet_p99_is_exactly_the_pooled_p99(self):
        registry = Registry()
        server = create_server(
            backend="fake", port=0, registry=registry, fleet_size=3,
            max_inflight=2, max_queue_depth=16, telemetry=True, slo=True,
        ).start()
        try:
            # Varied issues: scenario affinity would otherwise pin every
            # request to one replica and federation would be trivial.
            for i in range(12):
                status, body = _post(
                    server.base_url,
                    _payload(seed=100 + i, issue=f"{ISSUE} (variant {i})"),
                )
                assert status == 200

            fed = server.scheduler.federated_metrics_snapshot()
            family = fed["families"]["serve_latency_sketch_seconds"]
            accuracy = family.get("relative_accuracy", 0.01)
            fleet_body = None
            replica_bodies = []
            for series in family["series"]:
                if series["labels"].get("outcome") != "ok":
                    continue
                body = {k: v for k, v in series.items() if k != "labels"}
                if series["labels"]["replica"] == "fleet":
                    fleet_body = body
                else:
                    replica_bodies.append(body)
            assert fleet_body is not None
            assert len(replica_bodies) >= 2, (
                "load did not spread; federation proof needs >= 2 replicas"
            )

            pooled = dict(replica_bodies[0])
            for extra in replica_bodies[1:]:
                merge_sketch_series(pooled, extra)
            assert pooled["pos"] == fleet_body["pos"]
            assert pooled["count"] == fleet_body["count"]
            for q in (0.5, 0.9, 0.99):
                assert quantile_from_series(
                    fleet_body, q, accuracy
                ) == quantile_from_series(pooled, q, accuracy)

            # Exemplar linkage: a federated exemplar resolves to a trace.
            exemplars = fleet_body["exemplars"]
            assert exemplars, "federated sketch lost its exemplars"
            trace_id = exemplars[0]["trace_id"]
            status, trace = _get(server.base_url, f"/v1/trace/{trace_id}")
            assert status == 200
            assert trace["trace_id"] == trace_id

            # The text /metrics surface carries the federated series too.
            metrics = urllib.request.urlopen(
                server.base_url + "/metrics", timeout=5).read().decode()
            assert 'replica="fleet"' in metrics

            # Live /healthz + /v1/slo while telemetry is on.
            status, health = _get(server.base_url, "/healthz")
            assert health["welfare"]["drift"]["condition"] == "welfare_drift"
            assert "slo" in health
            status, slo = _get(server.base_url, "/v1/slo")
            assert {s["name"] for s in slo["specs"]} >= {
                "availability", "latency_p95", "welfare_drift"}
        finally:
            server.stop(drain=False, timeout=10.0)
            set_welfare_sink(None)

    def test_loadgen_reports_welfare_and_slo_blocks(self):
        from consensus_tpu.serve.loadgen import run_loadgen

        server = create_server(
            backend="fake", port=0, registry=Registry(), max_inflight=4,
            telemetry=True, slo=True,
        ).start()
        try:
            payloads = [_payload(seed=200 + i) for i in range(6)]
            report = run_loadgen(server.base_url, payloads, rate_rps=50.0,
                                 include_slo=True)
        finally:
            server.stop(drain=False, timeout=5.0)
            set_welfare_sink(None)
        assert report["availability"] == 1.0
        welfare = report["welfare"]
        assert welfare["evaluated"] == 6
        assert welfare["egalitarian_mean"] is not None
        assert welfare["min_agent_utility_p5"] is not None
        assert report["slo"]["worst"] in ("ok", "burning", "violated")
        assert "availability" in report["slo"]["specs"]
