"""prompt_style=reference: byte-equality against the reference sources.

The reference-faithful prompt builders (methods/prompts_reference.py) are a
behavioral contract: their value is EXACT textual identity with the
reference's habermas prompts.  Where the reference tree is mounted, these
tests extract the reference's own prompt functions (pure f-string builders)
with ast + exec and pin byte-equality on real scenario inputs.
"""

import ast
import pathlib

import pytest

from consensus_tpu.methods import prompts_reference as ref_prompts

REFERENCE = pathlib.Path("/root/reference/src/methods/habermas_machine.py")

ISSUE = "Should the library extend its opening hours?"
OPINIONS = [
    "Students need late-night study space.",
    'Staff costs must stay within the current budget, "strictly".',
    "Open later on weekends only.\n",
]
STATEMENTS = [
    "  Extend hours modestly. ",
    '"Open late on weekends."',
    "Pilot extended hours within budget.",
]


@pytest.fixture(scope="module")
def reference_fns():
    if not REFERENCE.exists():
        pytest.skip("reference tree not mounted")
    tree = ast.parse(REFERENCE.read_text())
    wanted = {
        "_generate_initial_prompt",
        "_hm_generate_opinion_only_ranking_prompt",
        "_generate_critique_prompt",
        "_generate_revised_statement_prompt",
    }
    namespace: dict = {}
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and node.name in wanted:
            source = ast.get_source_segment(REFERENCE.read_text(), node)
            exec(compile(source, str(REFERENCE), "exec"), namespace)
    missing = wanted - set(namespace)
    if missing:
        pytest.skip(f"reference functions not found: {missing}")
    return namespace


def test_initial_prompt_matches(reference_fns):
    assert ref_prompts.initial_prompt(ISSUE, OPINIONS) == reference_fns[
        "_generate_initial_prompt"
    ](ISSUE, OPINIONS)


def test_ranking_prompt_matches(reference_fns):
    assert ref_prompts.ranking_prompt(ISSUE, OPINIONS[0], STATEMENTS) == (
        reference_fns["_hm_generate_opinion_only_ranking_prompt"](
            ISSUE, OPINIONS[0], STATEMENTS
        )
    )


def test_critique_prompt_matches(reference_fns):
    assert ref_prompts.critique_prompt(ISSUE, OPINIONS[1], STATEMENTS[0]) == (
        reference_fns["_generate_critique_prompt"](ISSUE, OPINIONS[1], STATEMENTS[0])
    )


def test_revision_prompt_matches(reference_fns):
    opinions = {f"Agent {i}": op for i, op in enumerate(OPINIONS)}
    critiques = {f"Agent {i}": f"Critique {i}" for i in range(len(OPINIONS))}
    critiques["Agent 1"] = None  # the reference prints None rows verbatim
    assert ref_prompts.revision_prompt(
        ISSUE, opinions, STATEMENTS[2], critiques
    ) == reference_fns["_generate_revised_statement_prompt"](
        ISSUE, opinions, STATEMENTS[2], critiques
    )


def test_prompt_style_selectable_end_to_end():
    """Both styles run the full deliberation on the fake backend; an unknown
    style raises."""
    from consensus_tpu.backends.fake import FakeBackend
    from consensus_tpu.methods.habermas import HabermasMachineGenerator

    opinions = {f"Agent {i + 1}": op for i, op in enumerate(OPINIONS)}
    results = {}
    for style in ("tpu", "reference"):
        gen = HabermasMachineGenerator(
            backend=FakeBackend(),
            config={
                "num_candidates": 2,
                "num_rounds": 1,
                "seed": 5,
                "prompt_style": style,
            },
        )
        results[style] = gen.generate_statement(ISSUE, opinions)
        assert results[style] and not results[style].startswith("[ERROR")
    gen = HabermasMachineGenerator(
        backend=FakeBackend(), config={"prompt_style": "nope", "seed": 1}
    )
    with pytest.raises(ValueError):
        gen.generate_statement(ISSUE, opinions)
