"""Golden tests for Schulze aggregation and ranking parsers.

The numeric fixtures are the canonical electowiki Schulze examples plus the
Habermas-paper Figure 1 rounds, matching the correctness anchors the reference
pins in ``src/methods/tests/test_habermas_schulze.py`` and
``test_habermas_ranking_parsing.py`` (themselves adapted from Google's
``schulze_method_test.py``).  Passing these guarantees drop-in behavioural
parity of the social-choice core.
"""

import numpy as np
import pytest

from consensus_tpu.social_choice import (
    aggregate_schulze,
    check_arrow_format,
    check_response_format,
    compute_pairwise_defeats,
    compute_strongest_paths,
    extract_arrow_ranking,
    extract_statement,
    parse_arrow_ranking,
    process_ranking_response,
    rank_from_path_strengths,
    schulze_social_ranking,
)

# ---------------------------------------------------------------------------
# Electowiki fixtures: (name, ballots, defeats, path strengths, tied ranking)
# ---------------------------------------------------------------------------

ELECTOWIKI_CASES = [
    (
        "ew_30_voters_4_candidates",
        np.int32(
            5 * [[0, 2, 1, 3]]
            + 2 * [[0, 3, 1, 2]]
            + 3 * [[0, 3, 2, 1]]
            + 4 * [[1, 0, 2, 3]]
            + 3 * [[3, 1, 0, 2]]
            + 3 * [[3, 2, 0, 1]]
            + 1 * [[1, 3, 2, 0]]
            + 5 * [[2, 1, 3, 0]]
            + 4 * [[3, 2, 1, 0]]
        ),
        np.int32([[0, 11, 20, 14], [19, 0, 9, 12], [10, 21, 0, 17], [16, 18, 13, 0]]),
        np.int32([[0, 20, 20, 17], [19, 0, 19, 17], [19, 21, 0, 17], [18, 18, 18, 0]]),
        np.int32([1, 3, 2, 0]),  # D > C > A > B
    ),
    (
        "ew_9_voters_4_candidates",
        np.int32(
            3 * [[0, 1, 2, 3]] + 2 * [[1, 2, 3, 0]] + 2 * [[3, 1, 2, 0]] + 2 * [[3, 1, 0, 2]]
        ),
        np.int32([[0, 5, 5, 3], [4, 0, 7, 5], [4, 2, 0, 5], [6, 4, 4, 0]]),
        np.int32([[0, 5, 5, 5], [5, 0, 7, 5], [5, 5, 0, 5], [6, 5, 5, 0]]),
        np.int32([1, 0, 1, 0]),  # B=D > A=C
    ),
    (
        "ew_2_voters_4_candidates",
        np.int32([[0, 0, 1, 2], [0, 1, 3, 2]]),
        np.int32([[0, 1, 2, 2], [0, 0, 2, 2], [0, 0, 0, 1], [0, 0, 1, 0]]),
        np.int32([[0, 1, 2, 2], [0, 0, 2, 2], [0, 0, 0, 0], [0, 0, 0, 0]]),
        np.int32([0, 1, 2, 2]),  # A > B > C=D
    ),
    (
        "mh_5_voters_4_candidates",
        np.int32(2 * [[0, 1, 3, 2]] + [[1, 3, 2, 0]] + [[2, 3, 0, 1]] + [[2, 0, 3, 1]]),
        np.int32([[0, 4, 4, 2], [1, 0, 3, 3], [1, 2, 0, 1], [3, 2, 4, 0]]),
        np.int32([[0, 4, 4, 3], [3, 0, 3, 3], [0, 0, 0, 0], [3, 3, 4, 0]]),
        np.int32([0, 1, 2, 0]),  # A=D > B > C
    ),
    (
        "tbrc_2_voters_2_candidates",
        np.int32([[0, 1], [1, 0]]),
        np.int32([[0, 1], [1, 0]]),
        np.int32([[0, 0], [0, 0]]),
        np.int32([0, 0]),  # A=B
    ),
]


@pytest.mark.parametrize(
    "name,ballots,defeats,strengths,tied", ELECTOWIKI_CASES, ids=[c[0] for c in ELECTOWIKI_CASES]
)
def test_schulze_pipeline_stages(name, ballots, defeats, strengths, tied):
    np.testing.assert_array_equal(compute_pairwise_defeats(ballots), defeats)
    np.testing.assert_array_equal(compute_strongest_paths(defeats), strengths)
    np.testing.assert_array_equal(rank_from_path_strengths(strengths), tied)
    np.testing.assert_array_equal(schulze_social_ranking(ballots), tied)


# (case index, seed, expected ranking after random tie-breaking)
RANDOM_TIE_BREAK_CASES = [
    (0, 0, np.int32([1, 3, 2, 0])),  # no ties: unchanged
    (3, 1, np.int32([0, 2, 3, 1])),  # A=D tie broken -> A > D > B > C
    (4, 0, np.int32([0, 1])),
    (4, 3, np.int32([1, 0])),
    (1, 1, np.int32([2, 0, 3, 1])),  # B=D > A=C -> D > B > A > C
    (2, 2, np.int32([0, 1, 2, 3])),  # C=D broken -> A > B > C > D
]


@pytest.mark.parametrize("case_idx,seed,expected", RANDOM_TIE_BREAK_CASES)
def test_aggregate_schulze_random_tie_breaking(case_idx, seed, expected):
    ballots = ELECTOWIKI_CASES[case_idx][1]
    agent_rankings = {f"agent_{i}": row for i, row in enumerate(ballots)}
    result = aggregate_schulze(
        agent_rankings, ballots.shape[1], seed=seed, tie_breaking_method="random"
    )
    assert result is not None
    np.testing.assert_array_equal(result, expected)
    if ballots.shape[1] > 1:
        assert np.unique(result).size == result.size


FIGURE_1_CASES = [
    (
        "figure1_opinion_round",
        np.int32(
            [[0, 1, 2, 3], [1, 0, 3, 2], [3, 0, 1, 2], [1, 2, 3, 0], [2, 1, 3, 0]]
        ),
        np.int32([2, 0, 3, 1]),  # B > D > A > C
    ),
    (
        "figure1_critique_round",
        np.int32(
            [[2, 0, 1, 1], [0, 2, 1, 1], [2, 1, 1, 0], [1, 2, 0, 0], [3, 1, 0, 2]]
        ),
        np.int32([2, 1, 0, 0]),  # C=D > B > A
    ),
]


@pytest.mark.parametrize("name,ballots,expected", FIGURE_1_CASES, ids=[c[0] for c in FIGURE_1_CASES])
def test_schulze_figure1_rounds(name, ballots, expected):
    np.testing.assert_array_equal(schulze_social_ranking(ballots), expected)


@pytest.mark.parametrize(
    "matrix",
    [
        np.int32([[0, 1, 1], [1, 1, 1], [1, 1, 0]]),  # non-zero diagonal
        np.int32([[0, 1, 1], [1, 0, 1]]),  # non-square
    ],
)
def test_schulze_invalid_matrices_raise(matrix):
    with pytest.raises(ValueError):
        compute_strongest_paths(matrix)
    with pytest.raises(ValueError):
        rank_from_path_strengths(matrix)


def test_aggregate_schulze_drops_failed_agents_and_handles_empty():
    ballots = ELECTOWIKI_CASES[0][1]
    agent_rankings = {f"agent_{i}": row for i, row in enumerate(ballots)}
    agent_rankings["failed"] = None
    result = aggregate_schulze(agent_rankings, 4, seed=0)
    np.testing.assert_array_equal(result, ELECTOWIKI_CASES[0][4])

    assert aggregate_schulze({"a": None}, 4) is None
    # Shape mismatch -> None
    assert aggregate_schulze({"a": np.int32([0, 1])}, 4) is None


# ---------------------------------------------------------------------------
# Response / arrow-ranking parsing
# ---------------------------------------------------------------------------


def test_check_response_format():
    assert check_response_format("<answer>Explanation\n<sep>\nA > B > C</answer>")
    assert not check_response_format("Explanation\nA > B > C")


@pytest.mark.parametrize(
    "ranking_str,num_statements,expected",
    [
        ("A>B>C", 3, True),
        ("A=B>C>D", 4, True),
        ("A>B=C=D>E", 5, True),
        ("A=B=C", 3, True),
        ("A<B>C", 3, False),
        ("A>>B>C", 3, False),
        ("A>B>A", 3, False),
        ("A>B=B>C", 3, False),
        ("A>B>C>B", 4, False),
        ("A>>B", 2, False),
        ("A>B>>C", 3, False),
        ("A=>B", 2, False),
        ("A>B>", 2, False),
        (">A>B", 2, False),
        ("A=B=>C", 3, False),
        ("A>B=", 2, False),
        ("A=>B>C", 3, False),
        ("A>C", 3, False),
        ("A>B>C>D", 3, False),
        ("", 0, False),
    ],
)
def test_check_arrow_format(ranking_str, num_statements, expected):
    assert check_arrow_format(ranking_str, num_statements) is expected


@pytest.mark.parametrize(
    "text,expected",
    [
        ("Explanation\nA > B > C", "A>B>C"),
        ("Explanation\n  A  >  B  >  C", "A>B>C"),
        ("Explanation\n  A  =  B  >  C", "A=B>C"),
        ("Explanation\nA > B < C > D", "A>B"),
        ("Explanation", None),
    ],
)
def test_extract_arrow_ranking(text, expected):
    assert extract_arrow_ranking(text) == expected


@pytest.mark.parametrize(
    "arrow,n,expected",
    [
        ("B>A=D>C", 4, [1, 0, 2, 1]),
        ("A=B=C=D", 4, [0, 0, 0, 0]),
        ("A", 1, [0]),
        ("A>B", 3, None),  # missing C
    ],
)
def test_parse_arrow_ranking(arrow, n, expected):
    result = parse_arrow_ranking(arrow, n)
    if expected is None:
        assert result is None
    else:
        np.testing.assert_array_equal(result, np.array(expected))


@pytest.mark.parametrize(
    "response,n,expected_arr,expected_explanation",
    [
        (
            "<answer>Explanation\n<sep>\nB>A=D>C</answer>",
            4,
            [1, 0, 2, 1],
            "<answer>Explanation\n<sep>\nB>A=D>C</answer>",
        ),
        (
            "Explanation\nB>A=D>C",
            4,
            None,
            "INCORRECT_TEMPLATE: Explanation\nB>A=D>C",
        ),
        (
            "<answer>Explanation\n<sep>\nB<A=D>C</answer>",
            4,
            None,
            "INCORRECT_ARROW_RANKING: <answer>Explanation\n<sep>\nB<A=D>C</answer>",
        ),
        (
            "Final ranking: B>A=D>C",
            4,
            [1, 0, 2, 1],
            "Final ranking: B>A=D>C",
        ),
        (
            "<answer>Explanation\n<sep>\nA=B=C=D</answer>",
            4,
            [0, 0, 0, 0],
            "<answer>Explanation\n<sep>\nA=B=C=D</answer>",
        ),
        (
            "<answer>Explanation\n<sep>\nB>A>B</answer>",
            4,
            None,
            "INCORRECT_ARROW_RANKING: <answer>Explanation\n<sep>\nB>A>B</answer>",
        ),
        (
            "<answer>Explanation\n<sep>\nA>C</answer>",
            4,
            None,
            "INCORRECT_ARROW_RANKING: <answer>Explanation\n<sep>\nA>C</answer>",
        ),
    ],
)
def test_process_ranking_response(response, n, expected_arr, expected_explanation):
    ranking, explanation = process_ranking_response(response, n)
    if expected_arr is None:
        assert ranking is None
    else:
        np.testing.assert_array_equal(ranking, np.array(expected_arr))
    assert explanation == expected_explanation


def test_extract_statement_envelope():
    assert (
        extract_statement("<answer>reasoning here\n<sep>\nWe should invest.</answer>")
        == "We should invest."
    )
    # Truncated close tag tolerated
    assert (
        extract_statement("<answer>reasoning\n<sep>\nWe should invest more.")
        == "We should invest more."
    )
    assert extract_statement("no envelope at all") is None
    assert extract_statement("<answer>r<sep>tiny</answer>") is None  # <=5 chars
