"""Atomic-write and journal primitives (utils/io_atomic.py)."""

import json
import os

from consensus_tpu.utils.io_atomic import (
    JOURNAL_SCHEMA,
    JournalWriter,
    atomic_write_bytes,
    atomic_write_json,
    atomic_write_text,
    read_journal,
)


class TestAtomicWrite:
    def test_write_and_overwrite(self, tmp_path):
        target = tmp_path / "out.txt"
        atomic_write_text(target, "first")
        assert target.read_text() == "first"
        atomic_write_text(target, "second")
        assert target.read_text() == "second"

    def test_no_tmp_files_left_behind(self, tmp_path):
        target = tmp_path / "out.bin"
        atomic_write_bytes(target, b"payload")
        assert [p.name for p in tmp_path.iterdir()] == ["out.bin"]

    def test_creates_parent_dirs(self, tmp_path):
        target = tmp_path / "a" / "b" / "out.json"
        atomic_write_json(target, {"k": 1})
        assert json.loads(target.read_text()) == {"k": 1}

    def test_failure_leaves_destination_untouched(self, tmp_path):
        target = tmp_path / "out.json"
        atomic_write_text(target, "intact")
        try:
            atomic_write_json(target, {"bad": object()})
        except TypeError:
            pass
        else:  # pragma: no cover - defensive
            raise AssertionError("expected unserializable payload to raise")
        assert target.read_text() == "intact"
        assert [p.name for p in tmp_path.iterdir()] == ["out.json"]


class TestJournal:
    def test_append_and_read_roundtrip(self, tmp_path):
        path = tmp_path / "journal.jsonl"
        with JournalWriter(path) as journal:
            journal.append({"key": {"seed": 1}, "row": {"x": 1}})
            journal.append({"key": {"seed": 2}, "row": {"x": 2}})
        records = read_journal(path)
        assert [r["key"]["seed"] for r in records] == [1, 2]
        assert all(r["schema"] == JOURNAL_SCHEMA for r in records)

    def test_missing_file_reads_empty(self, tmp_path):
        assert read_journal(tmp_path / "nope.jsonl") == []

    def test_torn_tail_line_is_skipped(self, tmp_path):
        path = tmp_path / "journal.jsonl"
        with JournalWriter(path) as journal:
            journal.append({"row": {"x": 1}})
        # Simulate a crash mid-append: a partial, unterminated JSON line.
        with open(path, "a", encoding="utf-8") as fh:
            fh.write('{"schema": "' + JOURNAL_SCHEMA + '", "row": {"x')
        records = read_journal(path)
        assert len(records) == 1
        assert records[0]["row"] == {"x": 1}

    def test_wrong_schema_lines_are_skipped(self, tmp_path):
        path = tmp_path / "journal.jsonl"
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(json.dumps({"schema": "other.v9", "row": {}}) + "\n")
            fh.write(json.dumps({"schema": JOURNAL_SCHEMA, "row": {"ok": 1}})
                     + "\n")
        records = read_journal(path)
        assert len(records) == 1 and records[0]["row"] == {"ok": 1}

    def test_append_after_reopen_extends(self, tmp_path):
        path = tmp_path / "journal.jsonl"
        with JournalWriter(path) as journal:
            journal.append({"row": {"i": 0}})
        with JournalWriter(path) as journal:
            journal.append({"row": {"i": 1}})
        assert [r["row"]["i"] for r in read_journal(path)] == [0, 1]

    def test_fsync_visible_on_disk_immediately(self, tmp_path):
        path = tmp_path / "journal.jsonl"
        journal = JournalWriter(path)
        journal.append({"row": {"i": 0}})
        # Another reader (a resume in a new process) sees the record even
        # though the writer is still open.
        assert len(read_journal(path)) == 1
        assert os.path.getsize(path) > 0
        journal.close()
