"""Burn-rate SLO engine on a fake clock (ISSUE 16 tentpole layer 3).

Everything here is deterministic: the engine takes an injectable clock,
so the multi-window state machine is driven second-by-second with no
sleeps.  Pinned:

* **Spec validation**: ``SLOSpec.from_dict`` rejects unknown fields,
  out-of-range objectives, inverted windows, unknown signals; the engine
  rejects duplicate spec names.
* **Burn-rate math**: burn = (bad/total) / (1 - objective), exactly.
* **State machine**: the full ok -> burning -> violated walk under an
  injected fault (single-step — never ok -> violated in one evaluate),
  the blackbox dump ``slo_violated:<name>`` fired exactly once on the
  violated edge, and the recovery walk violated -> burning -> ok as the
  windows drain.
* **Poll signals**: ``kv_headroom`` floats classified against the spec
  threshold, ``welfare_drift`` status mappings and bare bools, ``None``
  and raising callables skipped without poisoning the window.
* **Windows**: one-second bucket aggregation and horizon pruning in
  ``_EventWindow``.
* **Registry surfaces**: ``slo_burn_rate``, ``slo_state`` and
  ``slo_transitions_total`` reflect the machine.
"""

import pytest

from consensus_tpu.obs.metrics import Registry
from consensus_tpu.obs.slo import (
    DEFAULT_SLO_SPECS,
    SLOEngine,
    SLOSpec,
    _EventWindow,
)


class FakeClock:
    def __init__(self, start=1000.0):
        self.t = start

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


AVAIL = SLOSpec(
    name="availability",
    signal="availability",
    objective=0.99,
    fast_window_s=60.0,
    slow_window_s=600.0,
    fast_burn_threshold=10.0,
    slow_burn_threshold=2.0,
)


def _engine(specs, registry=None, dumps=None, signals=None):
    clock = FakeClock()
    engine = SLOEngine(
        specs=specs,
        registry=registry,
        clock=clock,
        dump_blackbox=(dumps.append if dumps is not None else lambda r: None),
        signals=signals,
    )
    return engine, clock


def _spec_state(snapshot, name):
    return next(s for s in snapshot["specs"] if s["name"] == name)


def _gauge_value(registry, family, *label_values):
    fam = registry.snapshot()["families"][family]
    for series in fam["series"]:
        if tuple(series["labels"].values()) == label_values:
            return series["value"]
    return None


# ---------------------------------------------------------------------------
# Spec validation
# ---------------------------------------------------------------------------


class TestSpecValidation:
    def test_from_dict_round_trip(self):
        spec = SLOSpec.from_dict(AVAIL.to_dict())
        assert spec == AVAIL

    def test_from_dict_rejects_unknown_fields(self):
        with pytest.raises(ValueError, match="unknown SLO spec fields"):
            SLOSpec.from_dict({"name": "x", "signal": "latency",
                               "burn_limit": 3})

    def test_rejects_unknown_signal(self):
        with pytest.raises(ValueError, match="unknown SLO signal"):
            SLOSpec(name="x", signal="vibes")

    def test_rejects_objective_out_of_range(self):
        for bad in (0.0, 1.0, -0.5, 2.0):
            with pytest.raises(ValueError, match="objective"):
                SLOSpec(name="x", signal="availability", objective=bad)

    def test_rejects_inverted_windows(self):
        with pytest.raises(ValueError, match="fast_window_s"):
            SLOSpec(name="x", signal="availability",
                    fast_window_s=600.0, slow_window_s=60.0)

    def test_engine_rejects_duplicate_names(self):
        with pytest.raises(ValueError, match="duplicate"):
            SLOEngine(specs=[AVAIL, AVAIL])

    def test_engine_accepts_dict_specs(self):
        engine = SLOEngine(specs=[{"name": "lat", "signal": "latency",
                                   "objective": 0.95, "threshold": 2.0}])
        assert engine.specs[0].threshold == 2.0

    def test_default_specs_cover_all_signals(self):
        signals = {spec.signal for spec in DEFAULT_SLO_SPECS}
        assert signals == {"availability", "latency", "degraded",
                          "kv_headroom", "welfare_drift"}


# ---------------------------------------------------------------------------
# Burn-rate math
# ---------------------------------------------------------------------------


class TestBurnRate:
    def test_burn_is_bad_fraction_over_budget(self):
        engine, clock = _engine([AVAIL])
        for i in range(10):
            engine.record_request(ok=(i != 0), now=clock.t)
        snap = engine.evaluate(now=clock.t)
        burn = _spec_state(snap, "availability")["burn"]
        # 1 bad of 10, budget 0.01 -> burn exactly 10.0 in both windows.
        assert burn["fast"] == pytest.approx(10.0)
        assert burn["slow"] == pytest.approx(10.0)

    def test_no_events_is_zero_burn_ok(self):
        engine, clock = _engine([AVAIL])
        snap = engine.evaluate(now=clock.t)
        spec = _spec_state(snap, "availability")
        assert spec["burn"] == {"fast": 0.0, "slow": 0.0}
        assert spec["state"] == "ok"

    def test_latency_signal_thresholds_and_ignores_missing(self):
        spec = SLOSpec(name="lat", signal="latency", objective=0.5,
                       threshold=2.0)
        engine, clock = _engine([spec])
        engine.record_request(ok=True, latency_s=5.0, now=clock.t)   # bad
        engine.record_request(ok=True, latency_s=0.1, now=clock.t)   # good
        engine.record_request(ok=False, latency_s=None, now=clock.t)  # skip
        snap = engine.evaluate(now=clock.t)
        windows = _spec_state(snap, "lat")["windows"]
        assert windows["fast"] == {"good": 1, "bad": 1, "total": 2}

    def test_degraded_signal(self):
        spec = SLOSpec(name="deg", signal="degraded", objective=0.8)
        engine, clock = _engine([spec])
        engine.record_request(ok=True, degraded=True, now=clock.t)
        engine.record_request(ok=True, degraded=False, now=clock.t)
        snap = engine.evaluate(now=clock.t)
        assert _spec_state(snap, "deg")["windows"]["fast"]["bad"] == 1


# ---------------------------------------------------------------------------
# The state machine on a fake clock
# ---------------------------------------------------------------------------


class TestStateMachine:
    def _inject_fault(self, engine, clock, bad=8, good=12):
        for i in range(bad + good):
            engine.record_request(ok=(i >= bad), now=clock.t)

    def test_full_walk_fault_then_recovery(self):
        registry = Registry()
        dumps = []
        engine, clock = _engine([AVAIL], registry=registry, dumps=dumps)

        # Healthy baseline.
        for _ in range(20):
            engine.record_request(ok=True, now=clock.t)
        snap = engine.evaluate(now=clock.t)
        assert snap["worst"] == "ok"
        assert dumps == []

        # Latency-fault burst: 8 bad / 20 -> fast burn 40 >> 10.
        clock.advance(5.0)
        self._inject_fault(engine, clock)
        snap = engine.evaluate(now=clock.t)
        # Single-step: first evaluate only reaches burning.
        assert _spec_state(snap, "availability")["state"] == "burning"
        assert dumps == []

        # Same events still hot in BOTH windows -> violated, blackbox.
        clock.advance(1.0)
        snap = engine.evaluate(now=clock.t)
        assert _spec_state(snap, "availability")["state"] == "violated"
        assert snap["worst"] == "violated"
        assert dumps == ["slo_violated:availability"]

        # Fast window drains (bad burst ages out of 60s) -> burning.
        clock.advance(120.0)
        for _ in range(30):
            engine.record_request(ok=True, now=clock.t)
        snap = engine.evaluate(now=clock.t)
        assert _spec_state(snap, "availability")["state"] == "burning"

        # Slow window drains too -> ok.  One dump total.
        clock.advance(700.0)
        for _ in range(30):
            engine.record_request(ok=True, now=clock.t)
        snap = engine.evaluate(now=clock.t)
        assert _spec_state(snap, "availability")["state"] == "ok"
        assert snap["worst"] == "ok"
        assert dumps == ["slo_violated:availability"]

        # The walk is in the transition log, in order.
        walk = [(t["from"], t["to"]) for t in snap["transitions"]]
        assert walk == [("ok", "burning"), ("burning", "violated"),
                        ("violated", "burning"), ("burning", "ok")]

        # And mirrored in the registry.
        assert _gauge_value(registry, "slo_state", "availability") == 0
        assert _gauge_value(
            registry, "slo_transitions_total", "availability", "violated"
        ) == 1
        assert _gauge_value(
            registry, "slo_transitions_total", "availability", "ok"
        ) == 1

    def test_never_skips_from_ok_to_violated(self):
        engine, clock = _engine([AVAIL])
        self._inject_fault(engine, clock, bad=20, good=0)
        for _ in range(5):
            snap = engine.evaluate(now=clock.t)
            clock.advance(1.0)
        walk = [(t["from"], t["to"]) for t in snap["transitions"]]
        assert walk[0] == ("ok", "burning")
        assert walk[1] == ("burning", "violated")

    def test_blip_does_not_violate(self):
        # A short burst trips burning via the fast window, but the slow
        # window never gets hot enough once the burst ages out: the
        # machine must return to ok without ever touching violated.
        spec = SLOSpec(name="avail", signal="availability", objective=0.99,
                       fast_window_s=10.0, slow_window_s=600.0,
                       fast_burn_threshold=10.0, slow_burn_threshold=30.0)
        engine, clock = _engine([spec])
        engine.record_request(ok=False, now=clock.t)
        engine.record_request(ok=False, now=clock.t)
        for _ in range(8):
            engine.record_request(ok=True, now=clock.t)
        snap = engine.evaluate(now=clock.t)
        assert _spec_state(snap, "avail")["state"] == "burning"
        clock.advance(30.0)
        for _ in range(10):
            engine.record_request(ok=True, now=clock.t)
        snap = engine.evaluate(now=clock.t)
        assert _spec_state(snap, "avail")["state"] == "ok"
        states = {t["to"] for t in snap["transitions"]}
        assert "violated" not in states

    def test_violated_edge_dumps_parseable_blackbox(self, tmp_path):
        # The acceptance wiring end-to-end: the violated transition dumps
        # a real flight-recorder blackbox.json, parseable, with the SLO
        # trip as the dump reason.
        import json

        from consensus_tpu.obs.trace import FlightRecorder

        path = str(tmp_path / "blackbox.json")
        recorder = FlightRecorder(path=path)
        recorder.record_event("latency_fault_injected", fault="sleep")
        clock = FakeClock()
        engine = SLOEngine(
            specs=[AVAIL], clock=clock,
            dump_blackbox=lambda reason: recorder.dump(reason),
        )
        for _ in range(10):
            engine.record_request(ok=False, now=clock.t)
        engine.evaluate(now=clock.t)          # ok -> burning
        clock.advance(1.0)
        engine.evaluate(now=clock.t)          # burning -> violated: dump
        with open(path, encoding="utf-8") as handle:
            blackbox = json.load(handle)
        assert blackbox["reason"] == "slo_violated:availability"
        assert blackbox["events"][0]["kind"] == "latency_fault_injected"
        assert recorder.dumps == 1

    def test_dump_failure_does_not_poison_evaluate(self):
        def explode(reason):
            raise RuntimeError("disk full")

        clock = FakeClock()
        engine = SLOEngine(specs=[AVAIL], clock=clock, dump_blackbox=explode)
        for _ in range(10):
            engine.record_request(ok=False, now=clock.t)
        engine.evaluate(now=clock.t)
        clock.advance(1.0)
        snap = engine.evaluate(now=clock.t)  # violated edge -> dump raises
        assert _spec_state(snap, "availability")["state"] == "violated"


# ---------------------------------------------------------------------------
# Poll signals
# ---------------------------------------------------------------------------


KV_SPEC = SLOSpec(name="kv", signal="kv_headroom", objective=0.5,
                  threshold=0.10)
DRIFT_SPEC = SLOSpec(name="drift", signal="welfare_drift", objective=0.5)


class TestPollSignals:
    def test_kv_headroom_classified_against_threshold(self):
        values = iter([0.05, 0.50, None])
        engine, clock = _engine(
            [KV_SPEC], signals={"kv_headroom": lambda: next(values)})
        for _ in range(3):
            engine.sample_signals(now=clock.t)
        snap = engine.evaluate(now=clock.t)
        # 0.05 < 0.10 bad, 0.50 good, None skipped entirely.
        assert _spec_state(snap, "kv")["windows"]["fast"] == {
            "good": 1, "bad": 1, "total": 2}

    def test_welfare_drift_mapping_and_bool(self):
        values = iter([{"drifted": True}, {"drifted": False},
                       {"reason": "warming_up"}, True, False, None])
        engine, clock = _engine(
            [DRIFT_SPEC], signals={"welfare_drift": lambda: next(values)})
        for _ in range(6):
            engine.sample_signals(now=clock.t)
        snap = engine.evaluate(now=clock.t)
        # bad: {"drifted": True}, True.  good: {"drifted": False},
        # warming-up mapping, False.  skipped: None.
        assert _spec_state(snap, "drift")["windows"]["fast"] == {
            "good": 3, "bad": 2, "total": 5}

    def test_raising_signal_is_skipped(self):
        def broken():
            raise RuntimeError("stats endpoint down")

        engine, clock = _engine(
            [KV_SPEC], signals={"kv_headroom": broken})
        snap = engine.evaluate(now=clock.t)
        spec = _spec_state(snap, "kv")
        assert spec["windows"]["fast"]["total"] == 0
        assert spec["state"] == "ok"

    def test_unregistered_signal_is_skipped(self):
        engine, clock = _engine([KV_SPEC], signals={})
        snap = engine.evaluate(now=clock.t)
        assert _spec_state(snap, "kv")["windows"]["fast"]["total"] == 0

    def test_poll_fault_drives_state_machine(self):
        # objective 0.90 -> budget 0.10: an all-bad window burns at
        # exactly 10.0, meeting the default fast threshold.
        spec = SLOSpec(name="kv", signal="kv_headroom", objective=0.90,
                       threshold=0.10)
        values = iter([0.02] * 3 + [0.90] * 50)
        engine, clock = _engine(
            [spec], signals={"kv_headroom": lambda: next(values)})
        states = []
        for _ in range(3):
            snap = engine.evaluate(now=clock.t)
            states.append(_spec_state(snap, "kv")["state"])
            clock.advance(1.0)
        assert states == ["burning", "violated", "violated"]
        clock.advance(spec.slow_window_s + 10.0)
        for _ in range(10):
            snap = engine.evaluate(now=clock.t)
            clock.advance(1.0)
        assert _spec_state(snap, "kv")["state"] == "ok"


# ---------------------------------------------------------------------------
# _EventWindow internals
# ---------------------------------------------------------------------------


class TestEventWindow:
    def test_same_second_aggregates_into_one_bucket(self):
        window = _EventWindow(horizon_s=60.0)
        for _ in range(100):
            window.add(10.4, bad=False)
        window.add(10.9, bad=True)
        assert len(window._buckets) == 1
        assert window.counts(11.0, 60.0) == {
            "good": 100, "bad": 1, "total": 101}

    def test_window_cut_excludes_old_events(self):
        window = _EventWindow(horizon_s=600.0)
        window.add(0.0, bad=True)
        window.add(100.0, bad=False)
        assert window.counts(110.0, 60.0) == {
            "good": 1, "bad": 0, "total": 1}
        assert window.counts(110.0, 600.0)["total"] == 2

    def test_horizon_pruning_bounds_memory(self):
        window = _EventWindow(horizon_s=60.0)
        for second in range(1000):
            window.add(float(second), bad=False)
        window.counts(1000.0, 60.0)
        assert len(window._buckets) <= 62

    def test_transition_log_is_bounded(self):
        spec = SLOSpec(name="avail", signal="availability", objective=0.5,
                       fast_window_s=1.0, slow_window_s=2.0,
                       fast_burn_threshold=1.0, slow_burn_threshold=1.0)
        clock = FakeClock()
        engine = SLOEngine(specs=[spec], clock=clock,
                           dump_blackbox=lambda r: None, max_transitions=4)
        # Flap: alternate saturated-bad and drained windows.
        for i in range(20):
            engine.record_request(ok=False, now=clock.t)
            engine.evaluate(now=clock.t)
            clock.advance(5.0)
            engine.record_request(ok=True, now=clock.t)
            engine.evaluate(now=clock.t)
            clock.advance(5.0)
        assert len(engine.snapshot(now=clock.t)["transitions"]) == 4
