"""Model runtime tests: forward shapes, KV-cache parity, scoring, generation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensus_tpu.models.config import get_model_config
from consensus_tpu.models.generate import (
    generate_tokens,
    left_pad_positions,
    next_token_logits,
)
from consensus_tpu.models.sampling import sample_tokens
from consensus_tpu.models.tokenizer import ByteTokenizer
from consensus_tpu.models.transformer import (
    forward,
    init_params,
    make_cache,
    token_logprobs,
)

CFG = get_model_config("tiny-gemma2")
LLAMA_CFG = get_model_config("tiny-llama3")

# XLA's default matmul precision is bf16-grade (TPU-style) even on the CPU
# backend; exact-parity assertions pin the highest precision instead.
highest_precision = lambda: jax.default_matmul_precision("highest")


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def llama_params():
    return init_params(LLAMA_CFG, jax.random.PRNGKey(0))


def _random_tokens(key, batch, length, vocab):
    return jax.random.randint(key, (batch, length), 5, vocab)


@pytest.mark.parametrize("cfg_name", ["tiny-gemma2", "tiny-llama3"])
def test_forward_shapes(cfg_name):
    cfg = get_model_config(cfg_name)
    params_ = init_params(cfg, jax.random.PRNGKey(1))
    tokens = _random_tokens(jax.random.PRNGKey(2), 2, 7, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(7), (2, 7))
    valid = jnp.ones((2, 7), bool)
    logits, cache = forward(params_, cfg, tokens, positions, valid)
    assert logits.shape == (2, 7, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert cache is None
    assert np.isfinite(np.asarray(logits)).all()


def test_kv_cache_decode_matches_full_forward(params):
    """Prefill + step-by-step decode must reproduce the full-forward logits."""
    batch, s_ctx, extra = 2, 6, 4
    total = s_ctx + extra
    tokens = _random_tokens(jax.random.PRNGKey(3), batch, total, CFG.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(total), (batch, total))
    valid = jnp.ones((batch, total), bool)

    with highest_precision():
        full_logits, _ = forward(params, CFG, tokens, positions, valid)

        cache = make_cache(CFG, batch, total)
        prefill_logits, cache = forward(
            params, CFG, tokens[:, :s_ctx], positions[:, :s_ctx], valid[:, :s_ctx],
            cache, 0,
        )
        np.testing.assert_allclose(
            np.asarray(prefill_logits), np.asarray(full_logits[:, :s_ctx]), atol=2e-4
        )

        for t in range(extra):
            idx = s_ctx + t
            step_logits, cache = forward(
                params,
                CFG,
                tokens[:, idx : idx + 1],
                positions[:, idx : idx + 1],
                valid[:, idx : idx + 1],
                cache,
                idx,
            )
            np.testing.assert_allclose(
                np.asarray(step_logits[:, 0]), np.asarray(full_logits[:, idx]), atol=2e-4
            )


def test_left_padding_matches_unpadded(params):
    """A left-padded row must produce the same trailing logits as unpadded."""
    length, pad = 5, 3
    tokens_row = _random_tokens(jax.random.PRNGKey(4), 1, length, CFG.vocab_size)
    positions = jnp.arange(length)[None, :]
    valid = jnp.ones((1, length), bool)
    with highest_precision():
        ref_logits, _ = forward(params, CFG, tokens_row, positions, valid)

        padded = jnp.concatenate([jnp.zeros((1, pad), jnp.int32), tokens_row], axis=1)
        pvalid = jnp.concatenate([jnp.zeros((1, pad), bool), valid], axis=1)
        ppos = left_pad_positions(pvalid)
        pad_logits, _ = forward(params, CFG, padded, ppos, pvalid)

    np.testing.assert_allclose(
        np.asarray(pad_logits[:, pad:]), np.asarray(ref_logits), atol=2e-4
    )


def test_sliding_window_limits_context(params):
    """Tokens beyond the window must not influence local-layer attention.

    tiny-gemma2 has window 16 and alternating local/global layers, so only an
    indirect check is possible: logits must differ when a distant token
    changes for a *global* model but stay identical for a pure-local model
    with the change outside every window.
    """
    cfg = get_model_config(
        "tiny-gemma2", local_layer_pattern=(True,), sliding_window=4, n_layers=2
    )
    p = init_params(cfg, jax.random.PRNGKey(5))
    length = 12
    tokens_a = _random_tokens(jax.random.PRNGKey(6), 1, length, cfg.vocab_size)
    tokens_b = tokens_a.at[0, 0].set((tokens_a[0, 0] + 1) % cfg.vocab_size)
    positions = jnp.arange(length)[None, :]
    valid = jnp.ones((1, length), bool)
    with highest_precision():
        la, _ = forward(p, cfg, tokens_a, positions, valid)
        lb, _ = forward(p, cfg, tokens_b, positions, valid)
    # Last position is >window away from position 0: unchanged.
    np.testing.assert_allclose(np.asarray(la[0, -1]), np.asarray(lb[0, -1]), atol=2e-4)
    # Position 1 sees position 0: changed.
    assert not np.allclose(np.asarray(la[0, 1]), np.asarray(lb[0, 1]), atol=1e-4)


def test_token_logprobs_gather(params):
    tokens = _random_tokens(jax.random.PRNGKey(7), 2, 6, CFG.vocab_size)
    valid = jnp.ones((2, 6), bool)
    lps = token_logprobs(params, CFG, tokens, valid)
    assert lps.shape == (2, 6)
    assert np.asarray(lps[:, 0] == 0.0).all()
    assert (np.asarray(lps[:, 1:]) <= 0.0).all()

    positions = jnp.broadcast_to(jnp.arange(6), (2, 6))
    logits, _ = forward(params, CFG, tokens, positions, valid)
    manual = jax.nn.log_softmax(logits, axis=-1)
    expected = np.take_along_axis(
        np.asarray(manual[:, :-1]), np.asarray(tokens[:, 1:, None]), axis=-1
    )[..., 0]
    np.testing.assert_allclose(np.asarray(lps[:, 1:]), expected, atol=1e-5)


def test_generate_deterministic_greedy(params):
    tok = ByteTokenizer()
    prompt = _random_tokens(jax.random.PRNGKey(8), 2, 5, CFG.vocab_size)
    valid = jnp.ones((2, 5), bool)
    out1 = generate_tokens(
        params, CFG, prompt, valid, jax.random.PRNGKey(0), 6, temperature=0.0
    )
    out2 = generate_tokens(
        params, CFG, prompt, valid, jax.random.PRNGKey(1), 6, temperature=0.0
    )
    np.testing.assert_array_equal(np.asarray(out1.tokens), np.asarray(out2.tokens))
    assert out1.tokens.shape == (2, 6)


def test_generate_greedy_matches_manual_rollout(params):
    """Greedy generation must equal repeatedly argmaxing the full forward."""
    prompt = _random_tokens(jax.random.PRNGKey(9), 1, 4, CFG.vocab_size)
    steps = 5
    with highest_precision():
        out = generate_tokens(
            params,
            CFG,
            prompt,
            jnp.ones((1, 4), bool),
            jax.random.PRNGKey(0),
            steps,
            temperature=0.0,
        )
        seq = prompt
        expected = []
        for _ in range(steps):
            positions = jnp.arange(seq.shape[1])[None, :]
            logits, _ = forward(
                params, CFG, seq, positions, jnp.ones_like(seq, dtype=bool)
            )
            nxt = jnp.argmax(logits[:, -1], axis=-1)
            expected.append(int(nxt[0]))
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    assert list(np.asarray(out.tokens[0])) == expected


def test_generate_stops_at_eos(params):
    prompt = _random_tokens(jax.random.PRNGKey(10), 1, 4, CFG.vocab_size)
    valid = jnp.ones((1, 4), bool)
    # Find what greedy emits first, then declare it EOS: output must be empty.
    first = generate_tokens(
        params, CFG, prompt, valid, jax.random.PRNGKey(0), 1, temperature=0.0
    ).tokens[0, 0]
    out = generate_tokens(
        params,
        CFG,
        prompt,
        valid,
        jax.random.PRNGKey(0),
        4,
        temperature=0.0,
        eos_ids=jnp.asarray([first], jnp.int32),
    )
    assert int(out.num_generated[0]) == 0
    assert bool(out.hit_eos[0])
    assert np.asarray(out.tokens == 0).all()


def test_generate_early_exit_matches_full_run(params):
    """The decode while_loop exits once every row is done (early-exit path);
    a batch where all rows EOS immediately must return the same empty output
    a full-budget run would, for every row."""
    prompt = _random_tokens(jax.random.PRNGKey(11), 4, 6, CFG.vocab_size)
    valid = jnp.ones((4, 6), bool)
    first = generate_tokens(
        params, CFG, prompt, valid, jax.random.PRNGKey(0), 1, temperature=0.0
    ).tokens[:, 0]
    out = generate_tokens(
        params,
        CFG,
        prompt,
        valid,
        jax.random.PRNGKey(0),
        32,
        temperature=0.0,
        eos_ids=jnp.unique(first, size=4),
    )
    assert np.asarray(out.num_generated == 0).all()
    assert np.asarray(out.hit_eos).all()
    assert np.asarray(out.tokens == 0).all()


def test_generate_dummy_rows_start_done(params):
    """Bucket-padding rows (all-invalid prompts) must not pin the decode
    while_loop at the full budget: they start done and emit nothing, while
    real rows in the same batch are unaffected."""
    prompt = _random_tokens(jax.random.PRNGKey(12), 2, 6, CFG.vocab_size)
    valid = jnp.stack([jnp.ones((6,), bool), jnp.zeros((6,), bool)])
    out = generate_tokens(
        params, CFG, prompt, valid, jax.random.PRNGKey(1), 8, temperature=0.0
    )
    assert int(out.num_generated[1]) == 0
    assert np.asarray(out.tokens[1] == 0).all()
    solo = generate_tokens(
        params, CFG, prompt[:1], valid[:1], jax.random.PRNGKey(1), 8,
        temperature=0.0,
    )
    # The dummy row must not change the real row's output (greedy rows are
    # batch-independent; sampled rows need per-row keys for that, which the
    # backend supplies).
    np.testing.assert_array_equal(
        np.asarray(out.tokens[0]), np.asarray(solo.tokens[0])
    )


def test_next_token_logits_matches_forward(params):
    tokens = _random_tokens(jax.random.PRNGKey(11), 2, 5, CFG.vocab_size)
    valid = jnp.ones((2, 5), bool)
    ntl = next_token_logits(params, CFG, tokens, valid)
    positions = jnp.broadcast_to(jnp.arange(5), (2, 5))
    logits, _ = forward(params, CFG, tokens, positions, valid)
    np.testing.assert_allclose(np.asarray(ntl), np.asarray(logits[:, -1]), atol=1e-5)


def test_llama_variant_runs(llama_params):
    tokens = _random_tokens(jax.random.PRNGKey(12), 1, 6, LLAMA_CFG.vocab_size)
    valid = jnp.ones((1, 6), bool)
    lps = token_logprobs(llama_params, LLAMA_CFG, tokens, valid)
    assert np.isfinite(np.asarray(lps)).all()


# --- sampling ---------------------------------------------------------------


def test_sampling_greedy_topk_topp():
    logits = jnp.asarray([[1.0, 5.0, 2.0, -1.0]])
    token = sample_tokens(jax.random.PRNGKey(0), logits, temperature=0.0)
    assert int(token[0]) == 1
    # top_k=1 always picks argmax even at temperature 1.
    token = sample_tokens(jax.random.PRNGKey(3), logits, temperature=1.0, top_k=1)
    assert int(token[0]) == 1
    # top_p tiny keeps only the argmax.
    token = sample_tokens(jax.random.PRNGKey(4), logits, temperature=1.0, top_p=0.01)
    assert int(token[0]) == 1
    # logit bias can ban the argmax.
    bias = jnp.asarray([0.0, -1e9, 0.0, 0.0])
    token = sample_tokens(jax.random.PRNGKey(0), logits, temperature=0.0, logit_bias=bias)
    assert int(token[0]) == 2


def test_sampling_seed_determinism():
    logits = jax.random.normal(jax.random.PRNGKey(5), (3, 50))
    a = sample_tokens(jax.random.PRNGKey(7), logits, temperature=1.0)
    b = sample_tokens(jax.random.PRNGKey(7), logits, temperature=1.0)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --- tokenizer --------------------------------------------------------------


def test_byte_tokenizer_round_trip():
    tok = ByteTokenizer()
    text = "Hello, wörld! <|eot_id|> tail"
    ids = tok.encode(text)
    assert tok.decode(ids) == text
    assert tok.encode("<|eot_id|>") == [tok._special_to_id["<|eot_id|>"]]
    assert set(tok.eos_ids) <= set(range(tok.n_special))


def test_byte_tokenizer_chat_and_bias():
    tok = ByteTokenizer()
    prompt = tok.chat_prompt("hi", "sys")
    assert "[SYS]sys[/SYS]" in prompt and prompt.endswith("[ASSISTANT]")
    assert tok.raw_prompt("u", "s") == "s\n\nu"
    ids = tok.token_ids_containing(":")
    assert all(":" in tok.token_str(i) for i in ids)


def test_streamed_scoring_matches_naive():
    """token_logprobs_streamed == token_logprobs on a non-chunk-aligned vocab."""
    from consensus_tpu.models.config import get_model_config
    from consensus_tpu.models.transformer import (
        init_params,
        token_logprobs,
        token_logprobs_streamed,
    )

    config = get_model_config("tiny-gemma2", vocab_size=500, n_layers=2)
    params = init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (3, 12), 0, 500, jnp.int32)
    valid = jnp.arange(12)[None, :] < jnp.array([12, 9, 5])[:, None]

    naive = token_logprobs(params, config, tokens, valid)
    streamed = token_logprobs_streamed(params, config, tokens, valid, vocab_chunk=128)
    np.testing.assert_allclose(np.asarray(streamed), np.asarray(naive), atol=1e-4)
