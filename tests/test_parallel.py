"""Mesh/sharding tests on the 8-virtual-device CPU mesh (see conftest)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensus_tpu.models.config import get_model_config
from consensus_tpu.models.transformer import init_params, token_logprobs
from consensus_tpu.parallel import (
    init_train_state,
    lm_loss,
    make_mesh,
    shard_batch,
    shard_params,
    train_step,
)
from consensus_tpu.parallel.mesh import MODEL_AXIS


@pytest.fixture(scope="module")
def tiny_config():
    return get_model_config("tiny-gemma2", n_layers=2)


def test_make_mesh_shapes():
    plan = make_mesh(tp=2)
    assert plan.dp == 4 and plan.tp == 2 and plan.n_devices == 8
    assert plan.mesh.axis_names == ("data", "model")


def test_make_mesh_rejects_nondivisible_tp():
    with pytest.raises(ValueError):
        make_mesh(tp=3)


def test_shard_params_layout(tiny_config):
    plan = make_mesh(tp=2)
    params = init_params(tiny_config, jax.random.PRNGKey(0))
    sharded = shard_params(params, plan.mesh)
    # wq output features split over model axis.
    wq_spec = sharded["layers"]["wq"].sharding.spec
    assert wq_spec[-1] == MODEL_AXIS
    # Norm scales replicated.
    norm_spec = sharded["layers"]["attn_norm"].sharding.spec
    assert all(axis is None for axis in norm_spec)
    # Values untouched by placement.
    np.testing.assert_allclose(
        np.asarray(sharded["layers"]["wq"]), np.asarray(params["layers"]["wq"])
    )


def test_sharded_scoring_matches_single_device(tiny_config):
    """token_logprobs under a dp x tp mesh equals the unsharded result."""
    config = tiny_config
    params = init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, config.vocab_size, jnp.int32)
    valid = jnp.ones((8, 16), jnp.bool_)

    expected = token_logprobs(params, config, tokens, valid)

    plan = make_mesh(tp=2)
    p_sharded = shard_params(params, plan.mesh)
    t_sharded, v_sharded = shard_batch(plan.mesh, tokens, valid)
    got = token_logprobs(p_sharded, config, t_sharded, v_sharded)

    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-4)


@pytest.mark.xfail(
    strict=False,
    reason="random tiny-model weights on this jax build propose only "
    "special tokens, starving the beam (needs >= 2 viable candidates); "
    "the tp-identity claim itself is covered by the logprob tests above",
)
def test_token_search_session_under_tp_mesh():
    """The incremental search session (beam search driver) produces the same
    statement whether the backend's params are tensor-sharded or not — the
    session's fused step programs must partition cleanly over the mesh."""
    from consensus_tpu.backends.tpu import TPUBackend
    from consensus_tpu.methods import get_method_generator

    issue = "Should the town build a new library?"
    opinions = {
        "Agent 1": "Yes, libraries anchor the community.",
        "Agent 2": "Only if it does not raise taxes.",
    }
    cfg = {"beam_width": 2, "max_tokens": 5, "seed": 7}

    single = TPUBackend(model="tiny-gemma2", dtype="float32", max_context=256)
    sharded = TPUBackend(
        model="tiny-gemma2", dtype="float32", max_context=256, tp=2
    )
    s1 = get_method_generator("beam_search", single, cfg).generate_statement(
        issue, opinions
    )
    s2 = get_method_generator("beam_search", sharded, cfg).generate_statement(
        issue, opinions
    )
    assert s1 == s2


def test_train_step_runs_and_reduces_loss(tiny_config):
    config = tiny_config
    plan = make_mesh(tp=2)
    params = shard_params(init_params(config, jax.random.PRNGKey(0)), plan.mesh)
    params, opt_state, optimizer = init_train_state(params, learning_rate=1e-2)

    tokens = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0, config.vocab_size, jnp.int32)
    valid = jnp.ones((8, 16), jnp.bool_)
    tokens, valid = shard_batch(plan.mesh, tokens, valid)

    loss0 = float(lm_loss(params, config, tokens, valid))
    for _ in range(3):
        params, opt_state, loss = train_step(
            params, opt_state, config, optimizer, tokens, valid
        )
    assert np.isfinite(float(loss))
    assert float(lm_loss(params, config, tokens, valid)) < loss0


def test_dryrun_multichip_entrypoint():
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


def test_entry_traces_abstractly():
    """entry()'s step function must be jit-traceable (shape-level check —
    materializing 2B params on the test CPU would be wasteful)."""
    from consensus_tpu.models.config import get_model_config
    from consensus_tpu.models.transformer import init_params, forward

    config = get_model_config("gemma2-2b", n_layers=2)

    def build():
        return init_params(config, jax.random.PRNGKey(0), dtype=jnp.bfloat16)

    params_shape = jax.eval_shape(build)
    tokens = jax.ShapeDtypeStruct((4, 128), jnp.int32)
    valid = jax.ShapeDtypeStruct((4, 128), jnp.bool_)

    def score_step(params, tokens, valid):
        positions = jnp.maximum(jnp.cumsum(valid.astype(jnp.int32), axis=1) - 1, 0)
        logits, _ = forward(params, config, tokens, positions, valid)
        return logits

    out = jax.eval_shape(score_step, params_shape, tokens, valid)
    assert out.shape == (4, 128, config.vocab_size)


def test_checkpoint_roundtrip(tmp_path, tiny_config):
    from consensus_tpu.utils.checkpoint import restore_params, save_params

    params = init_params(tiny_config, jax.random.PRNGKey(3))
    save_params(str(tmp_path / "ckpt"), params)
    restored = restore_params(str(tmp_path / "ckpt"), template=params)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_restore_sharded(tmp_path, tiny_config):
    from consensus_tpu.parallel.mesh import param_shardings
    from consensus_tpu.utils.checkpoint import restore_params, save_params

    params = init_params(tiny_config, jax.random.PRNGKey(4))
    save_params(str(tmp_path / "ckpt"), params)
    plan = make_mesh(tp=2)
    shardings = param_shardings(params, plan.mesh)
    restored = restore_params(
        str(tmp_path / "ckpt"), template=params, shardings=shardings
    )
    wq = restored["layers"]["wq"]
    assert wq.sharding.spec[-1] == "model"
    np.testing.assert_allclose(
        np.asarray(wq), np.asarray(params["layers"]["wq"])
    )
