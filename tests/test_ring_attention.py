"""Ring attention == full attention, independent of sequence sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensus_tpu.parallel.ring_attention import (
    full_attention_reference,
    make_sequence_mesh,
    ring_self_attention,
)

B, S, H, HD = 2, 64, 4, 16


def _inputs(seed=0, ragged=False):
    key = jax.random.PRNGKey(seed)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, HD))
    k = jax.random.normal(kk, (B, S, H, HD))
    v = jax.random.normal(kv, (B, S, H, HD))
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))
    if ragged:
        lengths = jnp.array([S, S // 2])
        valid = jnp.arange(S)[None, :] < lengths[:, None]
    else:
        valid = jnp.ones((B, S), bool)
    return q, k, v, positions, valid


@pytest.mark.parametrize("n_devices", [2, 4, 8])
@pytest.mark.parametrize("causal", [True, False])
def test_matches_full_attention(n_devices, causal):
    q, k, v, positions, valid = _inputs()
    mesh = make_sequence_mesh(n_devices)
    ring = ring_self_attention(mesh, q, k, v, positions, valid, causal=causal)
    full = full_attention_reference(q, k, v, positions, valid, causal=causal)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(full), atol=2e-5)


def test_ragged_valid_masks():
    q, k, v, positions, valid = _inputs(seed=3, ragged=True)
    mesh = make_sequence_mesh(4)
    ring = ring_self_attention(mesh, q, k, v, positions, valid)
    full = full_attention_reference(q, k, v, positions, valid)
    # Compare only valid query rows; invalid rows are padding garbage.
    mask = np.asarray(valid)[:, :, None, None]
    np.testing.assert_allclose(
        np.asarray(ring) * mask, np.asarray(full) * mask, atol=2e-5
    )


def test_sharding_invariance():
    """Same inputs, different ring sizes -> same numbers."""
    q, k, v, positions, valid = _inputs(seed=7)
    out2 = ring_self_attention(make_sequence_mesh(2), q, k, v, positions, valid)
    out8 = ring_self_attention(make_sequence_mesh(8), q, k, v, positions, valid)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out8), atol=2e-5)


def test_causality():
    """Changing a future K/V must not change earlier query outputs."""
    q, k, v, positions, valid = _inputs(seed=9)
    mesh = make_sequence_mesh(4)
    base = np.asarray(ring_self_attention(mesh, q, k, v, positions, valid))
    k2 = k.at[:, -1].add(10.0)
    v2 = v.at[:, -1].add(10.0)
    perturbed = np.asarray(ring_self_attention(mesh, q, k2, v2, positions, valid))
    np.testing.assert_allclose(base[:, :-1], perturbed[:, :-1], atol=2e-5)
    assert not np.allclose(base[:, -1], perturbed[:, -1])
