"""Unit contract for ``consensus_tpu.obs``: metrics and spans.

Pins the parts downstream artifacts depend on: thread-safety of the
locked float adds (metrics.json totals must be exact under the batching
backend's concurrency), inclusive-``le`` histogram bucketing, the exact
Prometheus text exposition (metrics.prom is scraped verbatim), span-tree
nesting across threads via ``adopt``, and the snapshot algebra
(``diff_snapshots``/``merge_snapshots``) that run_sweep uses to roll
per-cell deltas into one aggregate.
"""

import json
import threading

import pytest

from consensus_tpu.obs import (
    DEFAULT_COUNT_BUCKETS,
    DEFAULT_TIME_BUCKETS,
    Registry,
    SpanTracer,
    diff_snapshots,
    diff_span_paths,
    exponential_buckets,
    get_registry,
    get_span_tracer,
    merge_snapshots,
)


def _series(snapshot, name, **labels):
    for entry in snapshot["families"][name]["series"]:
        if all(entry["labels"].get(k) == v for k, v in labels.items()):
            return entry
    raise AssertionError(f"no {name} series matching {labels}")


class TestConcurrency:
    def test_concurrent_counter_increments_are_exact(self):
        registry = Registry()
        counter = registry.counter("hits_total", labels=("worker",))
        n_threads, n_incs = 8, 500
        barrier = threading.Barrier(n_threads)

        def worker(tag):
            child = counter.labels(tag % 2)
            barrier.wait()
            for _ in range(n_incs):
                child.inc()

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = registry.snapshot()
        total = sum(s["value"] for s in snap["families"]["hits_total"]["series"])
        assert total == n_threads * n_incs
        assert _series(snap, "hits_total", worker="0")["value"] == 4 * n_incs

    def test_concurrent_histogram_observations_are_exact(self):
        registry = Registry()
        hist = registry.histogram("lat_seconds", buckets=(1.0, 10.0))
        n_threads, n_obs = 8, 400
        barrier = threading.Barrier(n_threads)

        def worker():
            barrier.wait()
            for _ in range(n_obs):
                hist.observe(0.5)

        threads = [threading.Thread(target=worker) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        series = _series(registry.snapshot(), "lat_seconds")
        assert series["count"] == n_threads * n_obs
        assert series["sum"] == pytest.approx(0.5 * n_threads * n_obs)
        assert series["bucket_counts"] == [n_threads * n_obs, 0, 0]


class TestHistogramBuckets:
    def test_boundaries_are_inclusive_upper_bounds(self):
        """Prometheus ``le`` semantics: a value exactly on a boundary lands
        in that boundary's bucket, one past it in the next."""
        registry = Registry()
        hist = registry.histogram("h", buckets=(1.0, 2.0, 4.0))
        for value in (1.0, 2.0, 2.0000001, 4.0, 100.0):
            hist.observe(value)
        series = _series(registry.snapshot(), "h")
        #              le=1  le=2  le=4  +Inf
        assert series["bucket_counts"] == [1, 1, 2, 1]
        assert series["count"] == 5
        assert series["min"] == 1.0 and series["max"] == 100.0

    def test_exponential_buckets_and_defaults(self):
        assert exponential_buckets(1.0, 2.0, 4) == (1.0, 2.0, 4.0, 8.0)
        with pytest.raises(ValueError):
            exponential_buckets(0.0, 2.0, 4)
        assert len(DEFAULT_TIME_BUCKETS) == 20
        assert DEFAULT_TIME_BUCKETS[0] == pytest.approx(1e-4)
        assert DEFAULT_COUNT_BUCKETS[0] == 1.0

    def test_counter_rejects_negative_and_kind_mismatch_raises(self):
        registry = Registry()
        counter = registry.counter("c")
        with pytest.raises(ValueError):
            counter.inc(-1)
        with pytest.raises(ValueError):
            registry.gauge("c")
        with pytest.raises(ValueError):
            registry.counter("c", labels=("extra",))


class TestPrometheusExposition:
    def _demo_registry(self):
        registry = Registry()
        requests = registry.counter(
            "demo_requests_total", help="Requests served.", labels=("method",)
        )
        requests.labels("GET").inc()
        requests.labels("GET").inc(2)
        requests.labels("POST").inc()
        registry.gauge("demo_inflight", help="In-flight requests.").set(3)
        latency = registry.histogram(
            "demo_latency_seconds",
            help="Latency.",
            labels=("method",),
            buckets=(1.0, 2.0, 4.0),
        )
        for value in (1.0, 3.0, 100.0):  # boundary, mid, overflow
            latency.labels("GET").observe(value)
        return registry

    GOLDEN = """\
# HELP demo_inflight In-flight requests.
# TYPE demo_inflight gauge
demo_inflight 3
# HELP demo_latency_seconds Latency.
# TYPE demo_latency_seconds histogram
demo_latency_seconds_bucket{method="GET",le="1"} 1
demo_latency_seconds_bucket{method="GET",le="2"} 1
demo_latency_seconds_bucket{method="GET",le="4"} 2
demo_latency_seconds_bucket{method="GET",le="+Inf"} 3
demo_latency_seconds_sum{method="GET"} 104
demo_latency_seconds_count{method="GET"} 3
# HELP demo_requests_total Requests served.
# TYPE demo_requests_total counter
demo_requests_total{method="GET"} 3
demo_requests_total{method="POST"} 1
"""

    def test_golden_text(self):
        assert self._demo_registry().to_prometheus() == self.GOLDEN

    def test_exposition_round_trips_against_snapshot(self):
        """Parse the text back sample-by-sample and check every value
        against the snapshot — the two export surfaces must agree."""
        registry = self._demo_registry()
        samples = {}
        for line in registry.to_prometheus().splitlines():
            if line.startswith("#"):
                continue
            sample, value = line.rsplit(" ", 1)
            samples[sample] = float(value)
        snap = registry.snapshot()
        get = _series(snap, "demo_requests_total", method="GET")
        assert samples['demo_requests_total{method="GET"}'] == get["value"]
        hist = _series(snap, "demo_latency_seconds", method="GET")
        assert samples['demo_latency_seconds_count{method="GET"}'] == hist["count"]
        assert samples['demo_latency_seconds_sum{method="GET"}'] == hist["sum"]
        assert (
            samples['demo_latency_seconds_bucket{method="GET",le="+Inf"}']
            == hist["count"]
        )

    def test_label_values_are_escaped(self):
        registry = Registry()
        registry.counter("c", labels=("p",)).labels('say "hi"\n\\x').inc()
        text = registry.to_prometheus()
        assert r'c{p="say \"hi\"\n\\x"} 1' in text

    def test_snapshot_is_json_serializable(self):
        payload = json.dumps(self._demo_registry().snapshot())
        assert "demo_requests_total" in payload


class TestSpans:
    def test_tree_nests_and_summary_stays_flat(self):
        tracer = SpanTracer()
        with tracer.span("outer"):
            with tracer.span("inner"):
                pass
            with tracer.span("inner"):
                pass
        tree = tracer.tree()
        assert [n["name"] for n in tree] == ["outer"]
        (outer,) = tree
        assert [(c["name"], c["count"]) for c in outer["children"]] == [
            ("inner", 2)
        ]
        summary = tracer.summary()
        assert summary["outer"]["count"] == 1
        assert summary["inner"]["count"] == 2
        assert summary["inner"]["total_s"] <= summary["outer"]["total_s"]

    def test_adopt_grafts_worker_threads_under_parent(self):
        """The experiment engine's pattern: pool workers adopt the
        ``experiment`` span's path so their spans nest under it."""
        tracer = SpanTracer()
        with tracer.span("experiment"):
            parent = tracer.current_path()

            def worker():
                with tracer.adopt(parent), tracer.span("generate"):
                    pass

            threads = [threading.Thread(target=worker) for _ in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        (root,) = tracer.tree()
        assert root["name"] == "experiment"
        (child,) = root["children"]
        assert (child["name"], child["count"]) == ("generate", 3)

    def test_orphan_paths_fall_back_to_root(self):
        tracer = SpanTracer()
        with tracer.span("a"), tracer.span("b"):
            pass
        window = diff_span_paths({("a",): (0.0, 1)}, tracer.snapshot_paths())
        # "a" has no new samples in the window, so ("a","b") is an orphan.
        (root,) = tracer.tree(window)
        assert root["name"] == "b" and root["children"] == []

    def test_diff_span_paths_drops_unsampled(self):
        tracer = SpanTracer()
        with tracer.span("x"):
            pass
        before = tracer.snapshot_paths()
        with tracer.span("y"):
            pass
        delta = diff_span_paths(before, tracer.snapshot_paths())
        assert set(delta) == {("y",)}


class TestSnapshotAlgebra:
    def test_diff_then_merge_recovers_totals(self):
        registry = Registry()
        counter = registry.counter("n_total", labels=("k",))
        hist = registry.histogram("t_seconds", buckets=(1.0, 2.0))

        counter.labels("a").inc(5)
        hist.observe(0.5)
        cut = registry.snapshot()
        counter.labels("a").inc(2)
        counter.labels("b").inc(1)
        hist.observe(1.5)
        hist.observe(9.0)
        delta = diff_snapshots(cut, registry.snapshot())

        assert _series(delta, "n_total", k="a")["value"] == 2
        assert _series(delta, "n_total", k="b")["value"] == 1
        h = _series(delta, "t_seconds")
        assert h["count"] == 2
        assert h["sum"] == pytest.approx(10.5)
        assert h["bucket_counts"] == [0, 1, 1]

        merged = merge_snapshots([cut, delta])
        assert _series(merged, "n_total", k="a")["value"] == 7
        mh = _series(merged, "t_seconds")
        assert mh["count"] == 3
        assert mh["sum"] == pytest.approx(11.0)
        assert mh["bucket_counts"] == [1, 1, 1]

    def test_diff_drops_untouched_series_and_keeps_gauges(self):
        registry = Registry()
        registry.counter("quiet_total").inc(3)
        registry.gauge("g").set(1)
        cut = registry.snapshot()
        registry.gauge("g").set(42)
        delta = diff_snapshots(cut, registry.snapshot())
        assert "quiet_total" not in delta["families"]
        assert _series(delta, "g")["value"] == 42

    def test_merge_gauges_last_write_wins(self):
        a = Registry()
        a.gauge("g").set(1)
        b = Registry()
        b.gauge("g").set(7)
        merged = merge_snapshots([a.snapshot(), b.snapshot()])
        assert _series(merged, "g")["value"] == 7


def test_global_singletons_are_stable():
    assert get_registry() is get_registry()
    assert get_span_tracer() is get_span_tracer()
