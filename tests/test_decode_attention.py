"""Pin the pallas decode-attention kernel against the einsum path.

The reference semantics are ``transformer.forward_trunk_tail``'s attention
block (trunk broadcast over slots + per-row tails); the kernel must
reproduce it for the session call sites' layout (shared query position,
left-padded trunk spans, tail columns <= write_col), with and without
Gemma-2's softcap/sliding-window.  Runs in interpret mode on CPU; the same
kernel compiles via Mosaic on TPU.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from consensus_tpu.ops.decode_attention import decode_attention


def einsum_reference(
    q, trunk_k, trunk_v, tail_k, tail_v, starts, qpos, write_col,
    n_slots, n_roles, scale, softcap=None, window=None,
):
    """The forward_trunk_tail attention block, re-expressed directly."""
    rows, h, hd = q.shape
    kv = trunk_k.shape[2]
    reps = h // kv
    w0 = trunk_k.shape[1]
    ts = tail_k.shape[1]

    qg = q.reshape(n_slots, n_roles, kv, reps, hd).astype(jnp.float32)
    ktr = trunk_k.transpose(0, 2, 1, 3).astype(jnp.float32)  # (R, KV, W0, hd)
    vtr = trunk_v.transpose(0, 2, 1, 3).astype(jnp.float32)
    ktl = tail_k.reshape(n_slots, n_roles, ts, kv, hd).astype(jnp.float32)
    vtl = tail_v.reshape(n_slots, n_roles, ts, kv, hd).astype(jnp.float32)

    lt = jnp.einsum("prgmd,rgtd->prgmt", qg, ktr)
    ls = jnp.einsum("prgmd,prtgd->prgmt", qg, ktl)
    logits = jnp.concatenate([lt, ls], axis=-1) * scale
    if softcap is not None:
        logits = softcap * jnp.tanh(logits / softcap)

    kiota = jnp.arange(w0)[None, :]
    trunk_ok = kiota >= starts[:, None]  # (R, W0)
    if window is not None:
        trunk_ok = trunk_ok & (qpos - (kiota - starts[:, None]) < window)
    cols = jnp.arange(ts)
    tail_ok = cols <= write_col
    if window is not None:
        tail_ok = tail_ok & (write_col - cols < window)
    mask = jnp.concatenate(
        [
            jnp.broadcast_to(trunk_ok[None], (n_slots, n_roles, w0)),
            jnp.broadcast_to(tail_ok[None, None], (n_slots, n_roles, ts)),
        ],
        axis=-1,
    )[:, :, None, None]
    logits = jnp.where(mask, logits, -1e30)
    weights = jax.nn.softmax(logits, axis=-1)
    attn = jnp.einsum("prgmt,rgtd->prgmd", weights[..., :w0], vtr) + jnp.einsum(
        "prgmt,prtgd->prgmd", weights[..., w0:], vtl
    )
    return attn.reshape(rows, h, hd)


def random_case(seed, n_slots=3, n_roles=2, kv=2, reps=2, hd=128, w0=96, ts=16):
    rng = np.random.default_rng(seed)
    h = kv * reps
    rows = n_slots * n_roles
    q = rng.standard_normal((rows, h, hd), dtype=np.float32)
    trunk_k = rng.standard_normal((n_roles, w0, kv, hd), dtype=np.float32)
    trunk_v = rng.standard_normal((n_roles, w0, kv, hd), dtype=np.float32)
    tail_k = rng.standard_normal((rows, ts, kv, hd), dtype=np.float32)
    tail_v = rng.standard_normal((rows, ts, kv, hd), dtype=np.float32)
    starts = np.array([5, 17][:n_roles] + [3] * max(0, n_roles - 2), np.int32)[
        :n_roles
    ]
    return q, trunk_k, trunk_v, tail_k, tail_v, starts


@pytest.mark.parametrize(
    "softcap,window",
    [(None, None), (50.0, None), (50.0, 48), (None, 24)],
)
def test_kernel_matches_einsum(softcap, window):
    n_slots, n_roles = 3, 2
    q, tk, tv, lk, lv, starts = random_case(0, n_slots=n_slots, n_roles=n_roles)
    qpos, write_col = 101, 7
    args = dict(
        n_slots=n_slots, n_roles=n_roles, scale=0.088, softcap=softcap,
        window=window,
    )
    ours = decode_attention(
        jnp.asarray(q), jnp.asarray(tk), jnp.asarray(tv),
        jnp.asarray(lk), jnp.asarray(lv), jnp.asarray(starts),
        jnp.asarray(qpos), jnp.asarray(write_col),
        block_k=64, interpret=True, **args,
    )
    ref = einsum_reference(
        jnp.asarray(q), jnp.asarray(tk), jnp.asarray(tv),
        jnp.asarray(lk), jnp.asarray(lv), jnp.asarray(starts),
        qpos, write_col, **args,
    )
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_kernel_first_step_write_col_zero():
    """write_col=0: only the current token's own tail column is visible."""
    n_slots, n_roles = 2, 3
    q, tk, tv, lk, lv, starts = random_case(
        1, n_slots=n_slots, n_roles=n_roles, w0=64, ts=8
    )
    starts = np.array([0, 9, 30], np.int32)
    ours = decode_attention(
        jnp.asarray(q), jnp.asarray(tk), jnp.asarray(tv),
        jnp.asarray(lk), jnp.asarray(lv), jnp.asarray(starts),
        jnp.asarray(63), jnp.asarray(0),
        n_slots=n_slots, n_roles=n_roles, scale=0.1,
        block_k=32, interpret=True,
    )
    ref = einsum_reference(
        jnp.asarray(q), jnp.asarray(tk), jnp.asarray(tv),
        jnp.asarray(lk), jnp.asarray(lv), jnp.asarray(starts),
        63, 0, n_slots=n_slots, n_roles=n_roles, scale=0.1,
    )
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_kernel_single_slot_trunk_session():
    """MCTS/lookahead trunk sessions: n_slots=1."""
    q, tk, tv, lk, lv, starts = random_case(
        2, n_slots=1, n_roles=3, w0=128, ts=32
    )
    starts = np.array([2, 0, 64], np.int32)
    ours = decode_attention(
        jnp.asarray(q), jnp.asarray(tk), jnp.asarray(tv),
        jnp.asarray(lk), jnp.asarray(lv), jnp.asarray(starts),
        jnp.asarray(140), jnp.asarray(12),
        n_slots=1, n_roles=3, scale=0.0884, softcap=30.0, window=96,
        block_k=64, interpret=True,
    )
    ref = einsum_reference(
        jnp.asarray(q), jnp.asarray(tk), jnp.asarray(tv),
        jnp.asarray(lk), jnp.asarray(lv), jnp.asarray(starts),
        140, 12, n_slots=1, n_roles=3, scale=0.0884, softcap=30.0, window=96,
    )
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_session_with_kernel_matches_einsum_path():
    """End-to-end: a beam session on the kernel-enabled config proposes the
    same tokens as the einsum path (tiny model, CPU interpret mode)."""
    from consensus_tpu.backends.session import SearchSpec
    from consensus_tpu.backends.tpu import TPUBackend, TPUTokenSearchSession

    spec = SearchSpec(
        ref_system="You draft consensus statements.",
        ref_user="Issue: trees.\nStatement:",
        agent_prompts=(
            ("Agent context.", "Opinion: plant more.\nStatement:"),
            ("Agent context.", "Opinion: too costly.\nStatement:"),
        ),
        n_slots=2,
        k=3,
        temperature=1.0,
        seed=11,
        sample=False,
        max_steps=4,
    )
    results = {}
    for use_kernel in (False, True):
        backend = TPUBackend(
            model="tiny-gemma2",
            dtype="float32",
            max_context=128,
            base_seed=0,
            use_flash_attention=False,
            use_decode_attention=use_kernel,
        )
        session = TPUTokenSearchSession(backend, spec)
        try:
            props = session.propose()
            step = session.advance_and_propose(
                [0, 1], [props[0][0], props[1][1]]
            )
            results[use_kernel] = [
                [(c.token_id, round(sum(c.agent_logprobs), 4)) for c in slot]
                for slot in step
            ]
        finally:
            session.close()
    assert results[True] == results[False]
