"""Wave-parallel MCTS: parity, determinism, and dispatch accounting.

The wave rewrite (methods/mcts.py) must be invisible at ``mcts_wave_size=1``
— bit-identical statements AND node-visit counts versus the pre-change
sequential search, pinned here against goldens captured from that code —
and must actually pay for itself at wave=8: the acceptance bar is >= 4x
fewer backend dispatches per statement at reference-default MCTS scale.
"""

import json
from pathlib import Path

import pytest

from consensus_tpu.backends.fake import FakeBackend
from consensus_tpu.methods.mcts import MCTSGenerator

ISSUE = "Should schools adopt a four-day week?"
OPINIONS = {
    "Agent 1": "A shorter week improves wellbeing for students and teachers.",
    "Agent 2": "Childcare burdens would fall on working parents.",
    "Agent 3": "Evidence on learning outcomes is mixed; pilot first.",
}

GOLDENS = json.loads(
    (Path(__file__).parent / "golden" / "mcts_wave1_goldens.json").read_text()
)


def run(config):
    gen = MCTSGenerator(FakeBackend(), dict(config))
    statement = gen.generate_statement(ISSUE, OPINIONS)
    return statement, gen.search_stats


@pytest.mark.parametrize("case", sorted(GOLDENS))
def test_wave1_matches_pre_change_sequential_search(case):
    """wave=1 replays the pre-change search exactly: same statement, same
    per-step root-children visit counts (goldens captured before the wave
    rewrite landed)."""
    golden = GOLDENS[case]
    statement, stats = run(golden["config"])
    assert statement == golden["statement"]
    got_log = [
        [list(pair) for pair in step] for step in stats["visit_log"]
    ]
    assert got_log == golden["visit_log"]


def test_wave1_explicit_config_matches_default():
    cfg = dict(GOLDENS["tiny"]["config"])
    cfg["mcts_wave_size"] = 1
    statement, stats = run(cfg)
    assert statement == GOLDENS["tiny"]["statement"]
    assert stats["collisions"] == 0  # virtual loss never engages at width 1


def test_wave8_deterministic_across_fresh_runs():
    cfg = dict(GOLDENS["small"]["config"])
    cfg["mcts_wave_size"] = 8
    s1, stats1 = run(cfg)
    s2, stats2 = run(cfg)
    assert s1 == s2
    assert stats1["visit_log"] == stats2["visit_log"]


def test_wave8_cuts_dispatches_at_least_4x():
    """Acceptance bar: at reference-default MCTS scale (num_simulations=50,
    expansion_sample_width=5, rollout_depth=10 — configs/examples), the obs
    dispatch counter shows >= 4x fewer backend calls per statement at wave=8
    vs wave=1.  ``pin_budget`` is the repo's timing mode: no terminal nodes,
    so every simulation issues real device work (without it the fake
    backend's early-EOS trees leave most simulations dispatch-free and the
    ratio measures tree shape, not batching)."""
    base = {
        "num_simulations": 50,
        "expansion_sample_width": 5,
        "max_tokens": 5,
        "rollout_depth": 10,
        "gamma": 0.99,
        "seed": 0,
        "pin_budget": True,
    }
    _, seq = run({**base, "mcts_wave_size": 1})
    _, wave = run({**base, "mcts_wave_size": 8})
    steps = len(seq["visit_log"])
    assert steps == len(wave["visit_log"]) == base["max_tokens"]
    per_seq = seq["device_dispatches"] / steps
    per_wave = wave["device_dispatches"] / steps
    assert per_seq / per_wave >= 4.0, (per_seq, per_wave)
    # The wave run really ran wide — and virtual loss had work to do.
    assert wave["waves"] < seq["waves"]
    assert wave["collisions"] > 0


def test_virtual_loss_reverts_exactly():
    """After every wave, transient virtual-loss visits must be unwound
    exactly — drift would contaminate UCB1 for the rest of the search.
    Each of the ``num_simulations`` selections backpropagates exactly one
    durable visit through the root, so the root's visit count must grow by
    exactly ``num_simulations`` per step (the tree advances into the best
    child, which carries its prior-step visits) iff no virtual visit
    leaked."""
    deltas = []
    snapshot = {}  # id(node) -> visits when its parent was the root

    class CapturingMCTS(MCTSGenerator):
        def _most_visited_child(self, root):  # shadows the staticmethod
            deltas.append(root.visits - snapshot.get(id(root), 0))
            snapshot.clear()
            snapshot.update(
                (id(child), child.visits)
                for child in root.children.values()
            )
            return MCTSGenerator._most_visited_child(root)

    cfg = dict(GOLDENS["small"]["config"])
    cfg["mcts_wave_size"] = 8
    gen = CapturingMCTS(FakeBackend(), cfg)
    gen.generate_statement(ISSUE, OPINIONS)
    assert deltas and gen.search_stats["collisions"] > 0
    assert deltas == [cfg["num_simulations"]] * len(deltas)


def test_search_stats_surface():
    statement, stats = run(GOLDENS["tiny"]["config"])
    assert stats["wave_size"] == 1
    assert stats["device_dispatches"] > 0
    assert stats["selections"] == stats["waves"]  # width 1: one per wave
