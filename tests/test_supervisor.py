"""Backend supervision: retry, integrity guards, bisection, breaker."""

import math

import numpy as np
import pytest

from consensus_tpu.backends import FakeBackend, GenerationRequest, ScoreRequest
from consensus_tpu.backends.base import (
    BackendIntegrityError,
    BackendLostError,
    PartialBatchError,
    TransientBackendError,
)
from consensus_tpu.backends.faults import FaultInjectingBackend
from consensus_tpu.backends.supervisor import CircuitBreaker, SupervisedBackend
from consensus_tpu.obs.metrics import Registry


def supervised(plan=None, **kwargs):
    registry = Registry()
    inner = FakeBackend()
    if plan is not None:
        inner = FaultInjectingBackend(inner, plan, registry=registry)
    kwargs.setdefault("sleep", lambda _s: None)
    return SupervisedBackend(inner, registry=registry, **kwargs), registry


class TestRetry:
    def test_transient_fault_retried_bit_identical(self):
        backend, registry = supervised(plan={"faults": [
            {"kind": "transient_error", "op": "generate", "call_index": 0}]})
        reqs = [GenerationRequest(user_prompt="p", seed=s, max_tokens=16)
                for s in range(2)]
        out = backend.generate(reqs)
        ref = FakeBackend().generate(reqs)
        assert [r.text for r in out] == [r.text for r in ref]
        assert 'supervisor_retries_total{op="generate"} 1' in \
            registry.to_prometheus()

    def test_retry_budget_exhaustion_raises_typed_error(self):
        backend, _ = supervised(
            plan={"faults": [
                {"kind": "transient_error", "op": "generate", "rate": 1.0}]},
            max_retries=2,
        )
        with pytest.raises(TransientBackendError, match="3 attempt"):
            backend.generate([GenerationRequest(user_prompt="p")])

    def test_backoff_is_exponential(self):
        delays = []
        registry = Registry()
        inner = FaultInjectingBackend(
            FakeBackend(),
            {"faults": [{"kind": "transient_error", "op": "generate",
                         "rate": 1.0}]},
            registry=registry,
        )
        backend = SupervisedBackend(
            inner, max_retries=3, backoff_s=0.01, registry=registry,
            sleep=delays.append,
        )
        with pytest.raises(TransientBackendError):
            backend.generate([GenerationRequest(user_prompt="p")])
        assert delays == [0.01, 0.02, 0.04]

    def test_empty_request_list_passthrough(self):
        backend, _ = supervised()
        assert backend.generate([]) == []


class TestIntegrityGuards:
    def test_all_rows_poisoned_raises_integrity(self):
        backend, _ = supervised(plan={"faults": [
            {"kind": "nan_logprobs", "op": "score", "call_index": 0}]})
        with pytest.raises(BackendIntegrityError, match="every row"):
            backend.score([ScoreRequest(context="c", continuation="x")])

    def test_one_poisoned_row_raises_partial_with_siblings(self):
        backend, _ = supervised(plan={"faults": [
            {"kind": "nan_logprobs", "op": "score", "call_index": 0,
             "row_index": 1}]})
        reqs = [ScoreRequest(context="c", continuation=f"row {i}")
                for i in range(3)]
        with pytest.raises(PartialBatchError) as excinfo:
            backend.score(reqs)
        err = excinfo.value
        assert set(err.row_errors) == {1}
        assert isinstance(err.row_errors[1], BackendIntegrityError)
        clean = FakeBackend().score(reqs)
        assert err.results[0].logprobs == clean[0].logprobs
        assert err.results[2].logprobs == clean[2].logprobs

    def test_poison_never_retried(self):
        backend, registry = supervised(plan={"faults": [
            {"kind": "inf_logprobs", "op": "score", "call_index": 0}]})
        with pytest.raises(BackendIntegrityError):
            backend.score([ScoreRequest(context="c", continuation="x")])
        # Family is registered but no retry series was ever incremented.
        assert "supervisor_retries_total{" not in registry.to_prometheus()

    def test_embed_guard(self):
        backend, _ = supervised(plan={"faults": [
            {"kind": "nan_logprobs", "op": "embed", "call_index": 0,
             "row_index": 0}]})
        with pytest.raises(PartialBatchError) as excinfo:
            backend.embed(["a", "b"])
        assert set(excinfo.value.row_errors) == {0}

    def test_guard_can_be_disabled(self):
        backend, _ = supervised(
            plan={"faults": [
                {"kind": "nan_logprobs", "op": "score", "call_index": 0}]},
            guard_nonfinite=False,
        )
        result = backend.score(
            [ScoreRequest(context="c", continuation="x")])[0]
        assert math.isnan(result.logprobs[0])  # caller opted out


class _RowPoisonBackend:
    """Raises deterministically (non-transient) for one specific request."""

    name = "row-poison"

    def __init__(self, bad_continuation):
        self.inner = FakeBackend()
        self.bad = bad_continuation

    def score(self, requests):
        if any(r.continuation == self.bad for r in requests):
            raise ValueError(f"poison row {self.bad!r}")
        return self.inner.score(requests)


class TestBisection:
    def test_bisection_isolates_poison_row(self):
        registry = Registry()
        backend = SupervisedBackend(
            _RowPoisonBackend("row 2"), registry=registry,
            sleep=lambda _s: None,
        )
        reqs = [ScoreRequest(context="c", continuation=f"row {i}")
                for i in range(4)]
        with pytest.raises(PartialBatchError) as excinfo:
            backend.score(reqs)
        err = excinfo.value
        assert set(err.row_errors) == {2}
        assert isinstance(err.row_errors[2], BackendIntegrityError)
        clean = FakeBackend().score(reqs)
        for i in (0, 1, 3):
            assert err.results[i].logprobs == clean[i].logprobs
        assert 'supervisor_bisections_total{op="score"} 1' in \
            registry.to_prometheus()

    def test_single_row_deterministic_failure_is_integrity_error(self):
        backend = SupervisedBackend(
            _RowPoisonBackend("only"), registry=Registry(),
            sleep=lambda _s: None,
        )
        with pytest.raises(BackendIntegrityError, match="deterministically"):
            backend.score([ScoreRequest(context="c", continuation="only")])


class TestCircuitBreaker:
    def make(self, **kwargs):
        self.now = 0.0
        kwargs.setdefault("failure_threshold", 2)
        kwargs.setdefault("cooldown_s", 10.0)
        return CircuitBreaker(
            clock=lambda: self.now, registry=Registry(), **kwargs
        )

    def test_opens_after_threshold_and_decays_to_half_open(self):
        breaker = self.make()
        breaker.record_failure()
        assert breaker.state == "closed"
        breaker.record_failure()
        assert breaker.state == "open"
        assert not breaker.allow_call()
        self.now += 10.0
        assert breaker.state == "half_open"
        assert breaker.allow_call()

    def test_half_open_admits_exactly_one_probe(self):
        breaker = self.make()
        breaker.record_failure(); breaker.record_failure()
        self.now += 10.0
        assert breaker.admission_allowed()
        assert not breaker.admission_allowed()
        assert not breaker.admission_allowed()

    def test_stale_probe_slot_recovers(self):
        breaker = self.make()
        breaker.record_failure(); breaker.record_failure()
        self.now += 10.0
        assert breaker.admission_allowed()
        # The probe request died silently; after another cooldown a new
        # probe is admitted rather than wedging the breaker forever.
        self.now += 10.0
        assert breaker.admission_allowed()

    def test_probe_success_closes(self):
        breaker = self.make()
        breaker.record_failure(); breaker.record_failure()
        self.now += 10.0
        assert breaker.admission_allowed()
        breaker.record_success()
        assert breaker.state == "closed"
        assert breaker.admission_allowed()

    def test_probe_failure_reopens_with_fresh_cooldown(self):
        breaker = self.make()
        breaker.record_failure(); breaker.record_failure()
        self.now += 10.0
        assert breaker.admission_allowed()
        breaker.record_failure()
        assert breaker.state == "open"
        self.now += 5.0
        assert breaker.state == "open"  # fresh cooldown, not the old one
        assert breaker.retry_after_s() >= 1.0

    def test_supervisor_fails_fast_when_open(self):
        registry = Registry()
        breaker = CircuitBreaker(
            failure_threshold=1, cooldown_s=100.0, registry=registry,
        )
        backend = SupervisedBackend(
            FakeBackend(), breaker=breaker, registry=registry,
            sleep=lambda _s: None,
        )
        breaker.record_failure()
        with pytest.raises(BackendLostError, match="circuit breaker open"):
            backend.generate([GenerationRequest(user_prompt="p")])

    def test_device_lost_counts_toward_breaker(self):
        backend, _ = supervised(
            plan={"faults": [
                {"kind": "device_lost", "op": "generate", "call_index": 0}]},
            failure_threshold=1, cooldown_s=100.0,
        )
        with pytest.raises(BackendLostError):
            backend.generate([GenerationRequest(user_prompt="p")])
        assert backend.circuit_breaker.state == "open"


class TestPassthrough:
    def test_properties_delegate(self):
        backend, _ = supervised()
        inner = FakeBackend()
        assert backend.token_counts.keys() == inner.token_counts.keys()
        assert backend.deterministic_greedy == bool(
            getattr(inner, "deterministic_greedy", False))

    def test_embed_returns_ndarray(self):
        backend, _ = supervised()
        vectors = backend.embed(["a", "b"])
        assert isinstance(vectors, np.ndarray) and vectors.shape[0] == 2

    def test_no_fused_session_escape_hatch(self):
        backend, _ = supervised()
        assert not hasattr(backend, "open_fused_token_search")
