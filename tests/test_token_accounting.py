"""Token-honest timing (VERDICT r2 #4): counters + pinned-budget mode.

The round-2 sweep's speedup columns were flattered by random-weight
degenerate statements (lookahead terminating after ~1 token).  Two fixes
certified here: every backend counts tokens actually generated/scored (so
s/1k-token normalization is possible), and a pinned-budget timing mode
forces every decoder to run its full token budget.
"""

import json

import pytest

from consensus_tpu.backends.base import GenerationRequest, ScoreRequest
from consensus_tpu.backends.fake import FakeBackend
from consensus_tpu.backends.tpu import TPUBackend
from consensus_tpu.experiment import Experiment


@pytest.fixture(scope="module")
def backend():
    return TPUBackend(model="tiny-gemma2", max_context=128, base_seed=0)


def test_generate_counts_emitted_tokens(backend):
    before = dict(backend.token_counts)
    results = backend.generate(
        [GenerationRequest(user_prompt="hello", max_tokens=6, seed=1)]
    )
    emitted = len(results[0].token_ids)
    assert emitted >= 1
    assert backend.token_counts["generated"] - before["generated"] == emitted


def test_score_counts_continuation_tokens(backend):
    before = dict(backend.token_counts)
    results = backend.score(
        [ScoreRequest(context="a context", continuation=" scored text here")]
    )
    assert backend.token_counts["scored"] - before["scored"] == len(
        results[0].logprobs
    )


def test_pinned_budget_generates_full_window():
    pinned = TPUBackend(
        model="tiny-gemma2",
        max_context=128,
        base_seed=0,
        pin_generation_budget=True,
    )
    results = pinned.generate(
        [
            GenerationRequest(
                user_prompt=f"prompt {i}", max_tokens=12, seed=i, stop=("e",)
            )
            for i in range(4)
        ]
    )
    # No EOS exit, no stop-string truncation: every row emits max_tokens.
    assert all(len(r.token_ids) == 12 for r in results)
    assert all(r.finish_reason == "length" for r in results)


def test_experiment_writes_token_counts(tmp_path):
    config = {
        "experiment_name": "tok",
        "seed": 1,
        "num_seeds": 1,
        "scenario": {
            "issue": "Trees?",
            "agent_opinions": {"Agent 1": "yes", "Agent 2": "no"},
        },
        "models": {"generation_model": "fake"},
        "methods_to_run": ["best_of_n"],
        "best_of_n": {"n": 2, "max_tokens": 8},
        "concurrent_execution": False,
        "output_dir": str(tmp_path),
    }
    experiment = Experiment(config, backend=FakeBackend())
    experiment.run()
    payload = json.loads((experiment.run_dir / "token_counts.json").read_text())
    assert payload["statements"] == 1
    assert payload["tokens_generated"] > 0
    assert payload["tokens_scored"] > 0
    assert payload["s_per_1k_tokens"] > 0
    assert payload["pinned_budget"] is False


def test_timing_pin_budget_reaches_methods(tmp_path):
    """timing_pin_budget injects pin_budget into every method run config
    (lookahead/beam/mcts read it to disable terminators)."""
    config = {
        "experiment_name": "pin",
        "seed": 1,
        "scenario": {"issue": "i", "agent_opinions": {"A": "o"}},
        "methods_to_run": ["finite_lookahead"],
        "finite_lookahead": {"max_tokens": 4},
        "timing_pin_budget": True,
        "output_dir": str(tmp_path),
    }
    experiment = Experiment(config, backend=FakeBackend())
    runs = experiment._run_configs(seed=1)
    assert all(r["config"]["pin_budget"] for r in runs)


def test_pinned_lookahead_runs_full_budget(tmp_path):
    """With terminators disabled the lookahead statement accumulates one
    token per outer step — max_tokens tokens, never the 1-token degenerate
    path (VERDICT r2 weak #2)."""
    from consensus_tpu.methods import get_method_generator

    backend = TPUBackend(model="tiny-gemma2", max_context=128, base_seed=3)
    pinned = get_method_generator(
        "finite_lookahead",
        backend,
        {"max_tokens": 6, "branching_factor": 2, "max_depth": 2,
         "seed": 5, "pin_budget": True},
        "tiny-gemma2",
    )
    before = dict(backend.token_counts)
    pinned.generate_statement("Issue?", {"A": "op a", "B": "op b"})
    generated = backend.token_counts["generated"] - before["generated"]
    # 6 outer steps x 1 trunk token each (the final step's token is appended
    # host-side without a session advance, so >= max_tokens - 1).
    assert generated >= 5
