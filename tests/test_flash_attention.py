"""Flash-attention kernel numerics vs the XLA reference (interpret mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensus_tpu.ops.flash_attention import flash_attention

B, S, H, HD = 2, 128, 2, 32


def _inputs(seed=0, ragged=False):
    key = jax.random.PRNGKey(seed)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, HD))
    k = jax.random.normal(kk, (B, S, H, HD))
    v = jax.random.normal(kv, (B, S, H, HD))
    lengths = jnp.array([S, S // 3]) if ragged else jnp.array([S, S])
    return q, k, v, lengths


def _reference(q, k, v, lengths, softcap=None, window=None, causal=True, starts=None):
    seq = q.shape[1]
    positions = jnp.broadcast_to(jnp.arange(seq), (q.shape[0], seq))
    if starts is None:
        starts = jnp.zeros_like(lengths)
    valid = (positions >= starts[:, None]) & (positions < (starts + lengths)[:, None])
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if softcap is not None:
        logits = softcap * jnp.tanh(logits / softcap)
    mask = valid[:, None, None, :] & valid[:, None, :, None]
    if causal:
        mask = mask & (positions[:, None, None, :] <= positions[:, None, :, None])
    if window is not None:
        mask = mask & (
            positions[:, None, :, None] - positions[:, None, None, :] < window
        )
    logits = jnp.where(mask, logits, -1e30)
    weights = jax.nn.softmax(logits, axis=-1)
    weights = jnp.where(mask.any(-1, keepdims=True), weights, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


def _valid_mask(lengths, seq):
    return (np.arange(seq)[None, :] < np.asarray(lengths)[:, None])[:, :, None, None]


@pytest.mark.parametrize("blocks", [(128, 128), (64, 32), (32, 64)])
def test_matches_reference_causal(blocks):
    q, k, v, lengths = _inputs()
    out = flash_attention(
        q, k, v, lengths, block_q=blocks[0], block_k=blocks[1], interpret=True
    )
    ref = _reference(q, k, v, lengths)
    mask = _valid_mask(lengths, S)
    np.testing.assert_allclose(
        np.asarray(out) * mask, np.asarray(ref) * mask, atol=2e-5
    )


def test_softcap_and_window():
    """Gemma-2 local layers: softcap 50, sliding window."""
    q, k, v, lengths = _inputs(seed=2)
    out = flash_attention(
        q, k, v, lengths, softcap=50.0, window=16,
        block_q=64, block_k=64, interpret=True,
    )
    ref = _reference(q, k, v, lengths, softcap=50.0, window=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ragged_lengths():
    q, k, v, lengths = _inputs(seed=3, ragged=True)
    out = flash_attention(q, k, v, lengths, block_q=64, block_k=64, interpret=True)
    ref = _reference(q, k, v, lengths)
    mask = _valid_mask(lengths, S)
    np.testing.assert_allclose(
        np.asarray(out) * mask, np.asarray(ref) * mask, atol=2e-5
    )


def test_non_causal():
    q, k, v, lengths = _inputs(seed=4)
    out = flash_attention(
        q, k, v, lengths, causal=False, block_q=64, block_k=64, interpret=True
    )
    ref = _reference(q, k, v, lengths, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_non_block_multiple_seq_pads():
    """seq not a block multiple is padded internally and sliced back."""
    key = jax.random.PRNGKey(6)
    q = jax.random.normal(key, (1, 100, 2, 32))
    k = jax.random.normal(jax.random.fold_in(key, 1), (1, 100, 2, 32))
    v = jax.random.normal(jax.random.fold_in(key, 2), (1, 100, 2, 32))
    lengths = jnp.array([77])
    out = flash_attention(q, k, v, lengths, block_q=64, block_k=64, interpret=True)
    ref = _reference(q, k, v, lengths)
    assert out.shape == (1, 100, 2, 32)
    mask = _valid_mask(lengths, 100)
    np.testing.assert_allclose(
        np.asarray(out) * mask, np.asarray(ref) * mask, atol=2e-5
    )


def test_left_padded_spans():
    """Regression: valid span [start, start+length) with start > 0 — the
    left-padded layout TPUBackend.next_token_logprobs/embed feed forward()."""
    q, k, v, _ = _inputs(seed=7)
    lengths = jnp.array([S, S // 3])
    starts = jnp.array([0, S - S // 3])  # row 1 left-padded
    out = flash_attention(
        q, k, v, lengths, starts, block_q=64, block_k=64, interpret=True
    )
    ref = _reference(q, k, v, lengths, starts=starts)
    pos = np.arange(S)[None, :]
    mask = (
        (pos >= np.asarray(starts)[:, None])
        & (pos < np.asarray(starts + lengths)[:, None])
    )[:, :, None, None]
    np.testing.assert_allclose(
        np.asarray(out) * mask, np.asarray(ref) * mask, atol=2e-5
    )


def test_left_padded_spans_windowed():
    q, k, v, _ = _inputs(seed=8)
    lengths = jnp.array([S // 2, S - 8])
    starts = jnp.array([S - S // 2, 8])
    out = flash_attention(
        q, k, v, lengths, starts, softcap=50.0, window=16,
        block_q=64, block_k=64, interpret=True,
    )
    ref = _reference(q, k, v, lengths, softcap=50.0, window=16, starts=starts)
    pos = np.arange(S)[None, :]
    mask = (
        (pos >= np.asarray(starts)[:, None])
        & (pos < np.asarray(starts + lengths)[:, None])
    )[:, :, None, None]
    np.testing.assert_allclose(
        np.asarray(out) * mask, np.asarray(ref) * mask, atol=2e-5
    )


def test_model_forward_with_flash_matches_naive():
    """tiny-gemma2 (GQA + softcap + alternating sliding-window layers):
    scoring with use_flash_attention=True equals the einsum path."""
    from consensus_tpu.models.config import get_model_config
    from consensus_tpu.models.transformer import init_params, token_logprobs

    naive_cfg = get_model_config("tiny-gemma2", n_layers=4)
    flash_cfg = get_model_config("tiny-gemma2", n_layers=4, use_flash_attention=True)
    params = init_params(naive_cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (3, 32), 0, 512, jnp.int32)
    valid = jnp.arange(32)[None, :] < jnp.array([32, 20, 9])[:, None]

    naive = token_logprobs(params, naive_cfg, tokens, valid)
    flash = token_logprobs(params, flash_cfg, tokens, valid)
    mask = np.asarray(valid)
    np.testing.assert_allclose(
        np.asarray(flash) * mask, np.asarray(naive) * mask, atol=5e-4
    )


def test_next_token_logits_left_padded_flash_matches_naive():
    """Regression (VERDICT r1 #1): beam/MCTS/lookahead propose tokens through
    next_token_logits on LEFT-padded batches; flash must equal naive there."""
    from consensus_tpu.models.config import get_model_config
    from consensus_tpu.models.generate import next_token_logits
    from consensus_tpu.models.transformer import init_params

    naive_cfg = get_model_config("tiny-gemma2", n_layers=4)
    flash_cfg = get_model_config("tiny-gemma2", n_layers=4, use_flash_attention=True)
    params = init_params(naive_cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (3, 32), 0, 512, jnp.int32)
    lengths = jnp.array([32, 20, 9])
    valid = jnp.arange(32)[None, :] >= (32 - lengths)[:, None]  # left-padded

    naive = next_token_logits(params, naive_cfg, tokens, valid)
    flash = next_token_logits(params, flash_cfg, tokens, valid)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(naive), atol=5e-4)


def test_embed_forward_left_padded_flash_matches_naive():
    from consensus_tpu.backends.tpu import _embed_forward
    from consensus_tpu.models.config import get_model_config
    from consensus_tpu.models.transformer import init_params

    naive_cfg = get_model_config("tiny-gemma2", n_layers=4)
    flash_cfg = get_model_config("tiny-gemma2", n_layers=4, use_flash_attention=True)
    params = init_params(naive_cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(2), (3, 32), 0, 512, jnp.int32)
    lengths = jnp.array([32, 13, 5])
    valid = jnp.arange(32)[None, :] >= (32 - lengths)[:, None]

    naive = _embed_forward(params, naive_cfg, tokens, valid)
    flash = _embed_forward(params, flash_cfg, tokens, valid)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(naive), atol=5e-4)
