"""Request-WAL primitives (serve/wal.py): torn tails, replay plans,
resolved-twice rejection, replay hash verification, and the lease.

Every test drives :class:`RequestWAL` directly against a tmp_path state
dir — the scheduler/server integration (real crash -> replay -> re-ask)
lives in tests/test_durability.py.
"""

import json
import os

import pytest

from consensus_tpu.obs.metrics import Registry
from consensus_tpu.serve.wal import (
    DEFAULT_LEASE_TTL_S,
    LEASE_FILENAME,
    WAL_FILENAME,
    WALIntegrityError,
    WALLeaseHeld,
    RequestWAL,
    result_hash,
)


def _wal(tmp_path, **kwargs):
    kwargs.setdefault("registry", Registry())
    return RequestWAL(tmp_path, **kwargs)


class TestResultHash:
    def test_volatile_keys_do_not_change_the_hash(self):
        base = {"statement": "s", "welfare": {"egalitarian": 0.5}}
        stamped = dict(base, generation_time_s=1.23, served_by="r1",
                       served_tier="full", idempotent_replay=True)
        assert result_hash(base) == result_hash(stamped)

    def test_answer_changes_change_the_hash(self):
        assert result_hash({"statement": "a"}) != result_hash(
            {"statement": "b"})

    def test_non_dict_hashes_to_none(self):
        assert result_hash(None) is None
        assert result_hash("text") is None


class TestJournalLifecycle:
    def test_admitted_without_resolved_is_the_replay_plan(self, tmp_path):
        wal = _wal(tmp_path)
        wal.record_admitted("r-1", "k1", {"issue": "a"})
        wal.record_admitted("r-2", "k2", {"issue": "b"})
        wal.record_resolved("r-1", "completed", "k1", "hash1")
        wal.close()  # crash: no seal

        recovered = _wal(tmp_path)
        plan = recovered.unresolved()
        assert [r["request_id"] for r in plan] == ["r-2"]
        assert plan[0]["request"] == {"issue": "b"}
        assert recovered.recovered_sealed is False
        assert recovered.stats()["recovered_unresolved"] == 1

    def test_sealed_journal_replays_nothing(self, tmp_path):
        wal = _wal(tmp_path)
        wal.record_admitted("r-1", "k1", {"issue": "a"})
        wal.record_resolved("r-1", "completed", "k1", None)
        wal.seal()

        recovered = _wal(tmp_path)
        assert recovered.unresolved() == []
        assert recovered.recovered_sealed is True

    def test_torn_tail_is_truncated_on_replay(self, tmp_path):
        wal = _wal(tmp_path)
        wal.record_admitted("r-1", "k1", {"issue": "a"})
        wal.record_admitted("r-2", "k2", {"issue": "b"})
        wal.close()
        # Simulate the crash tearing the final line mid-write: r-2's
        # admitted record loses its tail.  The record was never
        # acknowledged, so dropping it is lossless — only r-1 replays.
        path = tmp_path / WAL_FILENAME
        lines = path.read_bytes().splitlines(keepends=True)
        path.write_bytes(b"".join(lines[:-1]) + lines[-1][: len(lines[-1]) // 2])

        recovered = _wal(tmp_path)
        assert [r["request_id"] for r in recovered.unresolved()] == ["r-1"]

    def test_resolved_twice_is_rejected(self, tmp_path):
        wal = _wal(tmp_path)
        wal.record_admitted("r-1", "k1", {"issue": "a"})
        wal.record_resolved("r-1", "completed", "k1", "h")
        with pytest.raises(WALIntegrityError):
            wal.record_resolved("r-1", "completed", "k1", "h")

    def test_resolved_without_admitted_is_rejected(self, tmp_path):
        wal = _wal(tmp_path)
        with pytest.raises(WALIntegrityError):
            wal.record_resolved("ghost", "completed", None, None)

    def test_readmission_after_crash_restart_is_legal(self, tmp_path):
        # An entry may be admitted once per life; the recovered WAL must
        # accept the replay's re-admission and its (single) resolution.
        wal = _wal(tmp_path)
        wal.record_admitted("r-1", "k1", {"issue": "a"})
        wal.close()
        recovered = _wal(tmp_path)
        recovered.record_admitted("r-1", "k1", {"issue": "a"})
        recovered.record_resolved("r-1", "completed", "k1", "h")
        assert recovered.stats()["unresolved"] == 0


class TestReplayIdempotency:
    def test_matching_hash_passes_verification(self, tmp_path):
        value = {"statement": "s", "welfare": {"egalitarian": 0.4}}
        wal = _wal(tmp_path)
        wal.record_admitted("r-1", "k1", {"issue": "a"})
        wal.record_resolved("r-1", "completed", "k1", result_hash(value))
        wal.close()

        recovered = _wal(tmp_path)
        # A replay may carry different volatile stamps; only the answer
        # must match the journaled hash.
        recovered.verify_replay("r-1", dict(value, served_by="r9",
                                            idempotent_replay=True))

    def test_mismatching_hash_is_a_loud_integrity_error(self, tmp_path):
        wal = _wal(tmp_path)
        wal.record_admitted("r-1", "k1", {"issue": "a"})
        wal.record_resolved(
            "r-1", "completed", "k1", result_hash({"statement": "original"}))
        wal.close()

        recovered = _wal(tmp_path)
        with pytest.raises(WALIntegrityError):
            recovered.verify_replay("r-1", {"statement": "DIFFERENT"})

    def test_unrecorded_request_passes_vacuously(self, tmp_path):
        wal = _wal(tmp_path)
        wal.verify_replay("never-seen", {"statement": "anything"})


class TestLease:
    def test_fresh_foreign_lease_refuses_takeover(self, tmp_path):
        clock = [1000.0]
        first = _wal(tmp_path, clock=lambda: clock[0], owner="server-A")
        assert first.stats()["lease_owner"] == "server-A"
        # A second process arrives while A's lease is fresh.
        with pytest.raises(WALLeaseHeld):
            _wal(tmp_path, clock=lambda: clock[0] + 1.0, owner="server-B")

    def test_stale_lease_is_taken_over(self, tmp_path):
        clock = [1000.0]
        _wal(tmp_path, clock=lambda: clock[0], owner="server-A")
        clock[0] += DEFAULT_LEASE_TTL_S + 1.0
        taken = _wal(tmp_path, clock=lambda: clock[0], owner="server-B")
        lease = json.loads((tmp_path / LEASE_FILENAME).read_text())
        assert lease["owner"] == "server-B"
        assert taken.stats()["lease_owner"] == "server-B"

    def test_same_owner_reacquires_its_own_fresh_lease(self, tmp_path):
        clock = [1000.0]
        _wal(tmp_path, clock=lambda: clock[0], owner="server-A").close()
        _wal(tmp_path, clock=lambda: clock[0] + 1.0, owner="server-A")

    def test_dead_pid_lease_is_stale_regardless_of_ttl(self, tmp_path):
        # The default owner is pid-<N>; a SIGKILL'd server's replacement
        # must not wait out the TTL when the holder is provably dead.
        wal = _wal(tmp_path)
        wal.close()
        lease = json.loads((tmp_path / LEASE_FILENAME).read_text())
        assert lease["owner"] == f"pid-{os.getpid()}"
        dead = 2 ** 22 + (os.getpid() % 1000)  # beyond default pid_max
        (tmp_path / LEASE_FILENAME).write_text(json.dumps(
            {"owner": f"pid-{dead}", "expires_at": lease["expires_at"]}))
        taken = _wal(tmp_path)
        assert taken.stats()["lease_owner"] == f"pid-{os.getpid()}"

    def test_seal_releases_the_lease(self, tmp_path):
        wal = _wal(tmp_path)
        assert (tmp_path / LEASE_FILENAME).exists()
        wal.seal()
        assert not (tmp_path / LEASE_FILENAME).exists()

    def test_crash_close_leaves_the_lease_on_disk(self, tmp_path):
        wal = _wal(tmp_path)
        wal.close()
        assert (tmp_path / LEASE_FILENAME).exists()
