"""Fleet serving: replica supervisor, health-gated failover routing,
tiered degradation (ISSUE 7).

The acceptance proofs:

* **Failover bit-identity**: a 3-replica fleet at 3x single-replica
  capacity, one replica killed mid-run — availability 1.0, every accepted
  result byte-identical to a serial single-backend run (failed-over
  requests included), ``fleet_failovers_total > 0``.
* **Router bypass pin**: ``create_server(fleet_size=1)`` runs the exact
  PR 6 single-scheduler path (no router object) and its responses stay
  byte-identical to it.
* **Tier routing**: under pressure the fleet lever routes to the smaller
  model tier and stamps ``degraded`` / ``degraded_reason="tier_routed"``
  / ``served_tier``.
* **Hedging**: a tail-slow primary gets a duplicate dispatch after
  ``hedge_after_s``; the fast copy wins, byte-identical.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from consensus_tpu.backends import FakeBackend, get_backend
from consensus_tpu.backends.base import BackendLostError
from consensus_tpu.backends.faults import FaultPlan
from consensus_tpu.obs.metrics import Registry
from consensus_tpu.serve import (
    ConsensusService,
    FleetRouter,
    Replica,
    RequestScheduler,
    SchedulerRejected,
    create_server,
    parse_request,
)
from consensus_tpu.serve.fleet import ReplicaKillSwitch
from consensus_tpu.serve.router import _rendezvous_weight

ISSUE = "Should we invest in public transport?"
OPINIONS = {
    "Agent 1": "Yes, buses are vital.",
    "Agent 2": "Only with congestion pricing.",
}


def _payload(seed=7, issue=ISSUE, **overrides):
    payload = {
        "issue": issue,
        "agent_opinions": dict(OPINIONS),
        "method": "best_of_n",
        "params": {"n": 2, "max_tokens": 16},
        "seed": seed,
        "request_id": f"req-{seed}",
    }
    payload.update(overrides)
    return payload


def _serial_statement(payload):
    """The PR 6 ground truth: one fresh FakeBackend, no fleet, no merge."""
    return ConsensusService(FakeBackend()).run(
        parse_request(payload))["statement"]


class SlowBackend:
    """FakeBackend with a per-dispatch delay so kills land mid-flight."""

    name = "slow-fake"

    def __init__(self, delay_s=0.03):
        self.inner = FakeBackend()
        self.delay_s = delay_s

    @property
    def deterministic_greedy(self):
        return self.inner.deterministic_greedy

    @property
    def token_counts(self):
        return self.inner.token_counts

    def generate(self, requests):
        time.sleep(self.delay_s)
        return self.inner.generate(requests)

    def score(self, requests):
        time.sleep(self.delay_s)
        return self.inner.score(requests)

    def next_token_logprobs(self, requests):
        time.sleep(self.delay_s)
        return self.inner.next_token_logprobs(requests)

    def embed(self, texts):
        time.sleep(self.delay_s)
        return self.inner.embed(texts)


def _fleet(n=3, *, registry=None, delay_s=0.03, tiers=None, backends=None,
           scheduler_options=None, **router_kwargs):
    registry = registry if registry is not None else Registry()
    options = {"max_inflight": 2, "max_queue_depth": 6,
               "default_timeout_s": 30.0}
    options.update(scheduler_options or {})
    replicas = [
        Replica(
            f"r{i}",
            backends[i] if backends is not None else SlowBackend(delay_s),
            tier=tiers[i] if tiers is not None else "full",
            registry=registry,
            scheduler_options=dict(options),
        )
        for i in range(n)
    ]
    return FleetRouter(replicas, registry=registry, **router_kwargs).start()


# ---------------------------------------------------------------------------
# kill switch + replica health
# ---------------------------------------------------------------------------


class TestKillSwitch:
    def test_passthrough_until_killed_then_lost_on_every_op(self):
        switch = ReplicaKillSwitch(FakeBackend())
        assert len(switch.embed(["probe"])) == 1  # passes through
        switch.kill("preempted")
        for op in ("generate", "score", "next_token_logprobs", "embed"):
            with pytest.raises(BackendLostError, match="preempted"):
                getattr(switch, op)([])


class TestReplicaHealth:
    def test_health_ladder(self):
        replica = Replica("r0", FakeBackend(), registry=Registry(),
                          scheduler_options={"max_inflight": 1})
        replica.start()
        assert replica.health == "healthy"
        replica.kill("test kill")
        assert replica.lost and replica.health == "lost"
        assert replica.lost_reason == "test kill"
        replica.shutdown(drain=False, timeout=5.0)

    def test_probe_timeout_marks_lost(self):
        class HangingBackend(FakeBackend):
            def embed(self, texts):
                time.sleep(5.0)
                return super().embed(texts)

        replica = Replica("r0", HangingBackend(), registry=Registry(),
                          supervise=False,
                          scheduler_options={"max_inflight": 1})
        assert replica.probe(timeout_s=0.1) is False
        assert replica.lost and replica.lost_reason == "probe_timeout"

    def test_passive_loss_from_supervisor_flag(self):
        plan = FaultPlan.replica_lost(call_index=0, op="score")
        replica = Replica("r0", FakeBackend(), registry=Registry(),
                          fault_plan=plan,
                          scheduler_options={"max_inflight": 1})
        from consensus_tpu.backends import ScoreRequest

        with pytest.raises(BackendLostError):
            replica.backend.score(
                [ScoreRequest(context="ctx", continuation="row")])
        # The supervisor latched backend_lost; health derives it with no
        # explicit mark.
        assert replica.lost and replica.health == "lost"


class TestReplicaLostFaultSpec:
    def test_after_s_fires_deterministically_on_a_fake_clock(self):
        from consensus_tpu.backends.faults import FaultInjectingBackend

        now = [0.0]
        backend = FaultInjectingBackend(
            FakeBackend(), FaultPlan.replica_lost(after_s=5.0),
            clock=lambda: now[0])
        assert len(backend.embed(["ok"])) == 1  # t=0: before the deadline
        now[0] = 4.99
        assert len(backend.embed(["still ok"])) == 1
        now[0] = 5.0
        with pytest.raises(BackendLostError):
            backend.embed(["gone"])
        with pytest.raises(BackendLostError):  # sticky, like a real loss
            backend.embed(["still gone"])

    def test_call_index_variant_and_validation(self):
        plan = FaultPlan.replica_lost(call_index=1, op="embed")
        from consensus_tpu.backends.faults import FaultInjectingBackend

        backend = FaultInjectingBackend(FakeBackend(), plan)
        assert len(backend.embed(["call 0"])) == 1
        with pytest.raises(BackendLostError):
            backend.embed(["call 1"])
        with pytest.raises(ValueError):
            FaultPlan.replica_lost()
        with pytest.raises(ValueError):
            FaultPlan.replica_lost(after_s=1.0, call_index=1)


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------


class TestRendezvousRouting:
    def test_same_scenario_same_replica(self):
        router = _fleet(3, delay_s=0.0)
        try:
            req = parse_request(_payload(seed=1))
            first = router.route_for(req)
            for seed in range(2, 6):
                assert router.route_for(
                    parse_request(_payload(seed=seed))) is first
        finally:
            router.shutdown(drain=False, timeout=5.0)

    def test_only_the_dead_replicas_scenarios_move(self):
        # Rendezvous minimal disruption: killing one replica remaps ONLY
        # the scenarios it owned; everything else stays put.
        names = ["r0", "r1", "r2"]
        issues = [f"scenario {i}" for i in range(40)]

        def winner(pool, issue):
            return max(pool,
                       key=lambda n: _rendezvous_weight(issue, n))

        before = {issue: winner(names, issue) for issue in issues}
        dead = "r1"
        survivors = [n for n in names if n != dead]
        for issue in issues:
            after = winner(survivors, issue)
            if before[issue] != dead:
                assert after == before[issue]
            else:
                assert after in survivors

    def test_draining_and_lost_replicas_are_not_candidates(self):
        router = _fleet(3, delay_s=0.0)
        try:
            req = parse_request(_payload(seed=1))
            primary = router.route_for(req)
            router.kill_replica(primary.name)
            rerouted = router.route_for(req)
            assert rerouted is not None and rerouted is not primary
        finally:
            router.shutdown(drain=False, timeout=5.0)

    def test_no_replica_rejection_when_everything_is_lost(self):
        router = _fleet(2, delay_s=0.0)
        try:
            for replica in router.replicas:
                router.kill_replica(replica.name)
            with pytest.raises(SchedulerRejected) as excinfo:
                router.submit(parse_request(_payload()))
            assert excinfo.value.reason == "no_replica"
        finally:
            router.shutdown(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# router bypass pin (fleet_size=1 == the PR 6 path)
# ---------------------------------------------------------------------------


class TestRouterBypass:
    def test_fleet_size_one_is_the_single_scheduler_path(self):
        server = create_server(
            backend=FakeBackend(), port=0, registry=Registry()).start()
        try:
            # Literally the PR 6 object graph: a RequestScheduler, not a
            # FleetRouter — bypass byte-identity is true by construction.
            assert isinstance(server.scheduler, RequestScheduler)
            assert not isinstance(server.scheduler, FleetRouter)
        finally:
            server.stop(drain=False, timeout=5.0)

    def test_bypass_response_byte_identical_to_serial(self):
        payload = _payload(seed=21)
        server = create_server(
            backend=FakeBackend(), port=0, registry=Registry()).start()
        try:
            status, body = _post(server.base_url, payload)
        finally:
            server.stop()
        assert status == 200
        assert body["statement"] == _serial_statement(payload)
        # No fleet stamps on the bypass path.
        assert "served_by" not in body and "served_tier" not in body


# ---------------------------------------------------------------------------
# failover acceptance
# ---------------------------------------------------------------------------


def _wait_all(tickets, timeout=60.0):
    threads = []
    for ticket in tickets:
        thread = threading.Thread(
            target=ticket.wait, args=(timeout,), daemon=True)
        thread.start()
        threads.append(thread)
    return threads


class TestFailoverAcceptance:
    def test_three_replica_fleet_survives_mid_run_kill_byte_identical(self):
        """The headline proof: 24 requests — more than 2x what one
        replica can hold (max_inflight 2 + queue 8) and within the
        3-replica fleet's aggregate capacity — one replica killed while
        serving: zero rejections, availability 1.0, failovers > 0, and
        every statement byte-identical to the serial single-backend run."""
        capacity = {"max_inflight": 2, "max_queue_depth": 8,
                    "default_timeout_s": 30.0}
        # A single replica with the same limits cannot even ADMIT this
        # burst — the fleet's capacity claim, measured not asserted.
        single = _fleet(1, delay_s=0.03, scheduler_options=capacity)
        try:
            with pytest.raises(SchedulerRejected):
                for i in range(24):
                    single.submit(parse_request(_payload(seed=100 + i)))
        finally:
            single.shutdown(drain=False, timeout=10.0)

        registry = Registry()
        router = _fleet(3, registry=registry, delay_s=0.03,
                        scheduler_options=capacity)
        payloads = [_payload(seed=100 + i) for i in range(24)]
        expected = {p["request_id"]: _serial_statement(p) for p in payloads}
        try:
            requests = [parse_request(p) for p in payloads]
            doomed = router.route_for(requests[0])
            tickets = [router.submit(req) for req in requests]  # none reject
            threads = _wait_all(tickets)
            # Kill the replica serving request 0 while it has work in
            # flight (its backend is slow, so the first dispatch is still
            # sleeping); its requests MUST fail over, not fail.
            deadline = time.monotonic() + 10.0
            while (doomed.scheduler.stats()["inflight"] == 0
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            router.kill_replica(doomed.name)
            for thread in threads:
                thread.join(timeout=60.0)

            results = [t.result() for t in tickets]  # raises on any failure
            assert all(t.outcome == "ok" for t in tickets)
            for req, result in zip(requests, results):
                assert result["statement"] == expected[req.request_id]
                assert result["served_by"] != doomed.name
                assert result["served_tier"] == "full"
            assert router.failovers_total > 0
            stats = router.stats()["fleet"]
            assert stats["lost"] == 1
            assert stats["failovers_total"] == router.failovers_total
            metrics = registry.to_prometheus()
            assert "fleet_failovers_total" in metrics
            assert "fleet_replicas_lost 1" in metrics
        finally:
            router.shutdown(drain=False, timeout=10.0)

    def test_failed_over_request_is_requeued_not_rerejected(self):
        # Survivor queues full at failover time: the fleet-admitted
        # request retries under its deadline instead of surfacing a 429.
        registry = Registry()
        router = _fleet(
            2, registry=registry, delay_s=0.05,
            scheduler_options={"max_inflight": 1, "max_queue_depth": 2},
        )
        try:
            requests = [parse_request(_payload(seed=300 + i))
                        for i in range(4)]
            doomed = router.route_for(requests[0])
            tickets = [router.submit(req) for req in requests]
            threads = _wait_all(tickets)
            deadline = time.monotonic() + 10.0
            while (doomed.scheduler.stats()["inflight"] == 0
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            router.kill_replica(doomed.name)
            for thread in threads:
                thread.join(timeout=60.0)
            for ticket in tickets:
                assert ticket.outcome == "ok", ticket._error
        finally:
            router.shutdown(drain=False, timeout=10.0)


# ---------------------------------------------------------------------------
# tier routing + hedging
# ---------------------------------------------------------------------------


class TestTierRouting:
    def test_pressure_routes_to_small_tier_and_stamps_degraded(self):
        router = _fleet(
            2, delay_s=0.0, tiers=["full", "small"],
            tier_enter_pressure=0.0,  # any pressure escalates immediately
            tier_min_dwell_s=0.0,
        )
        try:
            ticket = router.submit(parse_request(_payload(seed=5)))
            assert ticket.wait(30.0)
            result = ticket.result()
            assert result["served_tier"] == "small"
            assert result["served_by"] == "r1"
            assert result["degraded"] is True
            assert result["degraded_reason"] == "tier_routed"
            assert router.stats()["fleet"]["serving_tier"] == "small"
        finally:
            router.shutdown(drain=False, timeout=5.0)

    def test_default_tier_never_stamps_degraded(self):
        router = _fleet(2, delay_s=0.0, tiers=["full", "small"])
        try:
            ticket = router.submit(parse_request(_payload(seed=5)))
            assert ticket.wait(30.0)
            result = ticket.result()
            assert result["served_tier"] == "full"
            assert not result.get("degraded", False)
        finally:
            router.shutdown(drain=False, timeout=5.0)


class TestHedging:
    def test_tail_slow_primary_is_hedged_byte_identical(self):
        payload = _payload(seed=9)
        request = parse_request(payload)
        # Make whichever replica rendezvous picks for this scenario the
        # slow one, so the hedge fires and the fast copy wins.
        probe_names = ["r0", "r1"]
        winner = max(
            probe_names,
            key=lambda n: _rendezvous_weight(request.issue, n))
        backends = [
            SlowBackend(2.0) if f"r{i}" == winner else SlowBackend(0.0)
            for i in range(2)
        ]
        registry = Registry()
        router = _fleet(2, registry=registry, backends=backends,
                        hedge_after_s=0.05)
        try:
            ticket = router.submit(request)
            assert router.route_for(request).name == winner
            assert ticket.wait(20.0)
            result = ticket.result()
            assert result["served_by"] != winner  # the hedge won
            assert result["statement"] == _serial_statement(payload)
            assert ticket.hedged and router.hedges_total >= 1
            assert "fleet_hedges_total 1" in registry.to_prometheus()
        finally:
            router.shutdown(drain=False, timeout=10.0)


# ---------------------------------------------------------------------------
# HTTP surface + loadgen integration
# ---------------------------------------------------------------------------


def _post(base_url, payload, timeout=30.0):
    request = urllib.request.Request(
        base_url + "/v1/consensus",
        data=json.dumps(payload).encode("utf-8"),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(request, timeout=timeout) as response:
            return response.status, json.loads(response.read().decode())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read().decode())


class TestFleetHTTP:
    def test_healthz_aggregates_and_degrades_on_replica_loss(self):
        registry = Registry()
        server = create_server(
            backend="fake", port=0, registry=registry, fleet_size=3,
            max_inflight=2, max_queue_depth=8,
        ).start()
        try:
            status, body = _post(server.base_url, _payload(seed=11))
            assert status == 200 and body["served_by"]

            with urllib.request.urlopen(
                    server.base_url + "/healthz", timeout=5) as response:
                health = json.loads(response.read().decode())
            assert health["status"] == "ok"
            fleet = health["fleet"]
            assert fleet["size"] == 3 and fleet["healthy"] == 3
            assert fleet["availability"] == 1.0
            assert set(fleet["replicas"]) == {"r0", "r1", "r2"}
            for snap in fleet["replicas"].values():
                assert snap["tier"] == "full"
                assert snap["health"] == "healthy"
                assert "circuit_breaker" in snap

            server.scheduler.kill_replica("r0")
            with urllib.request.urlopen(
                    server.base_url + "/healthz", timeout=5) as response:
                health = json.loads(response.read().decode())
            assert health["status"] == "degraded"
            assert health["fleet"]["lost"] == 1
            assert health["fleet"]["replicas"]["r0"]["health"] == "lost"

            metrics = urllib.request.urlopen(
                server.base_url + "/metrics", timeout=5).read().decode()
            assert "fleet_replicas_healthy 2" in metrics
            assert "fleet_replicas_lost 1" in metrics
            assert "fleet_routed_total" in metrics
        finally:
            server.stop(timeout=10.0)

    def test_loadgen_reports_fleet_surface(self):
        from consensus_tpu.serve.loadgen import run_loadgen

        server = create_server(
            backend="fake", port=0, registry=Registry(), fleet_size=2,
            max_inflight=2, max_queue_depth=16,
        ).start()
        try:
            payloads = [_payload(seed=400 + i) for i in range(8)]
            report = run_loadgen(server.base_url, payloads, rate_rps=50.0)
        finally:
            server.stop(timeout=10.0)
        assert report["availability"] == 1.0
        assert report["fleet"]["size"] == 2
        assert sum(report["replica_request_counts"].values()) == 8
        assert report["failover_fraction"] == 0.0
