"""Theory-module tests: utilities, Frank–Wolfe, LPs, rollout equivalence."""

import numpy as np
import pytest

from consensus_tpu.theory import (
    compute_utilities,
    egalitarian_lottery,
    enumerate_leaves,
    generate_params,
    induced_policy_rollout,
    max_coalition_improvement,
    nash_welfare_lottery,
    nash_welfare_value,
)

B, L, D, N = 2, 3, 4, 3


@pytest.fixture(scope="module")
def utilities():
    v, w = generate_params(B, L, D, N, seed=5)
    U, leaves = compute_utilities(v, w, rho=2.0)
    return U, leaves


def test_enumerate_leaves_shape_and_order():
    leaves = np.asarray(enumerate_leaves(2, 3))
    assert leaves.shape == (8, 3)
    assert leaves[0].tolist() == [0, 0, 0]
    assert leaves[-1].tolist() == [1, 1, 1]
    # Row index equals the base-B digit interpretation (rollout relies on it).
    for i, row in enumerate(leaves):
        assert i == int("".join(map(str, row)), 2)


def test_utilities_positive_normalized(utilities):
    U, _ = utilities
    assert U.shape == (N, B**L)
    assert np.all(U > 0)
    assert np.allclose(U.max(axis=1), 1.0 + 1e-300)  # per-agent max-stabilized


def test_utilities_are_products_of_step_probs():
    """At rho=0 every step policy is uniform, so all leaves tie."""
    v, w = generate_params(B, L, D, N, seed=1)
    U, _ = compute_utilities(v, w, rho=0.0)
    assert np.allclose(U, U[:, :1])


def test_nash_lottery_on_simplex(utilities):
    U, _ = utilities
    p = nash_welfare_lottery(U)
    assert p.shape == (B**L,)
    assert np.all(p >= -1e-12)
    assert np.isclose(p.sum(), 1.0, atol=1e-6)


def test_nash_lottery_beats_baselines(utilities):
    U, _ = utilities
    p = nash_welfare_lottery(U)
    m = U.shape[1]
    assert nash_welfare_value(U, p) >= nash_welfare_value(U, np.ones(m) / m) - 1e-9
    best_leaf = np.zeros(m)
    best_leaf[int(np.argmax(U.sum(0)))] = 1.0
    assert nash_welfare_value(U, p) >= nash_welfare_value(U, best_leaf) - 1e-9


def test_egalitarian_lottery_maximin(utilities):
    U, _ = utilities
    p = egalitarian_lottery(U)
    assert np.isclose(p.sum(), 1.0, atol=1e-6)
    # Its min utility beats the uniform lottery's min utility.
    assert (U @ p).min() >= (U @ (np.ones(U.shape[1]) / U.shape[1])).min() - 1e-9


def test_nash_is_not_blockable(utilities):
    """The paper's claim: NW lottery alpha stays ~1 (in the core)."""
    U, _ = utilities
    alpha = max_coalition_improvement(U, nash_welfare_lottery(U))
    assert alpha <= 1.0 + 1e-4


def test_bad_lottery_is_blockable(utilities):
    """A degenerate lottery on the worst leaf should be blockable."""
    U, _ = utilities
    worst = np.zeros(U.shape[1])
    worst[int(np.argmin(U.sum(0)))] = 1.0
    alpha = max_coalition_improvement(U, worst)
    assert alpha > 1.0


def test_induced_rollout_matches_lottery(utilities):
    U, _ = utilities
    p = nash_welfare_lottery(U)
    _, tv = induced_policy_rollout(p, B, L, num_samples=50_000, seed=3)
    assert tv < 0.03  # sampling noise only
