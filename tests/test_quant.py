"""Int8 weight-only quantization (models/quant.py) correctness tests.

The quantized path must (a) bound per-weight error by construction,
(b) track the full-precision model's logprobs closely on every scoring
primitive (dense, streamed, tied and untied heads), and (c) drop into
TPUBackend as a config switch without changing any protocol semantics.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from consensus_tpu.backends.base import GenerationRequest, ScoreRequest
from consensus_tpu.backends.tpu import TPUBackend
from consensus_tpu.models import transformer as T
from consensus_tpu.models.config import get_model_config
from consensus_tpu.models.generate import generate_tokens, next_token_topk
from consensus_tpu.models.quant import (
    QTensor,
    dequantize,
    is_quantized,
    quantize,
    quantize_params,
)


def _tiny(name="tiny-gemma2", dtype=jnp.float32):
    cfg = get_model_config(name)
    params = T.init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
    return cfg, params


def _batch(cfg, b=4, s=24, seed=1):
    toks = jax.random.randint(jax.random.PRNGKey(seed), (b, s), 1, cfg.vocab_size)
    return toks, jnp.ones((b, s), bool)


class TestQTensor:
    def test_roundtrip_error_bounded_by_half_step(self):
        w = jax.random.normal(jax.random.PRNGKey(3), (16, 32)) * 0.1
        qt = quantize(w, contract_axis=-2)
        assert qt.q.dtype == jnp.int8
        err = jnp.abs(dequantize(qt) - w)
        # Symmetric absmax: |w - deq| <= scale/2 per output channel.
        assert bool(jnp.all(err <= qt.scale[0] / 2 + 1e-7))

    def test_scale_shapes_follow_contraction_axis(self):
        stacked = jax.random.normal(jax.random.PRNGKey(4), (3, 8, 16))
        assert quantize(stacked, contract_axis=-2).scale.shape == (3, 1, 16)
        table = jax.random.normal(jax.random.PRNGKey(5), (64, 8))
        assert quantize(table, contract_axis=-1).scale.shape == (64, 1)

    def test_zero_channel_quantizes_to_zero(self):
        w = jnp.zeros((4, 4))
        qt = quantize(w, contract_axis=-2)
        assert bool(jnp.all(dequantize(qt) == 0.0))

    def test_pytree_roundtrip_preserves_compute_dtype(self):
        qt = quantize(jnp.ones((4, 4), jnp.bfloat16), contract_axis=-2)
        leaves, treedef = jax.tree_util.tree_flatten(qt)
        rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
        assert rebuilt.dtype == jnp.bfloat16
        assert rebuilt.shape == (4, 4)


class TestQuantizedForward:
    def test_quantize_params_structure(self):
        cfg, params = _tiny()
        qp = quantize_params(params)
        assert is_quantized(qp) and not is_quantized(params)
        assert isinstance(qp["layers"]["wq"], QTensor)
        # Norms stay full precision.
        assert not isinstance(qp["layers"]["attn_norm"], QTensor)
        assert not isinstance(qp["final_norm"], QTensor)

    def test_token_logprobs_close_to_full_precision(self):
        cfg, params = _tiny()
        toks, valid = _batch(cfg)
        full = np.asarray(T.token_logprobs(params, cfg, toks, valid))
        quant = np.asarray(T.token_logprobs(quantize_params(params), cfg, toks, valid))
        assert np.max(np.abs(full - quant)) < 0.1
        assert np.mean(np.abs(full - quant)) < 0.02

    def test_streamed_matches_dense_under_quantization(self):
        cfg, params = _tiny()
        qp = quantize_params(params)
        toks, valid = _batch(cfg, seed=2)
        dense = np.asarray(T.token_logprobs(qp, cfg, toks, valid))
        streamed = np.asarray(
            T.token_logprobs_streamed(qp, cfg, toks, valid, vocab_chunk=64)
        )
        np.testing.assert_allclose(streamed, dense, atol=5e-3)

    def test_matmul_rejects_per_row_scaled_tables(self):
        table = quantize(jax.random.normal(jax.random.PRNGKey(6), (64, 8)), -1)
        with pytest.raises(ValueError, match="per-output-channel"):
            from consensus_tpu.models.quant import matmul

            matmul(jnp.ones((2, 64)), table)

    def test_streamed_logprobs_nonpositive_in_bfloat16(self):
        """The target-row path must round exactly like the LSE tile path —
        a mismatch shows up as logprobs above zero (code-review finding)."""
        cfg, params = _tiny(dtype=jnp.bfloat16)
        qp = quantize_params(params)
        toks, valid = _batch(cfg, b=8, s=32, seed=7)
        lp = np.asarray(
            T.token_logprobs_streamed(qp, cfg, toks, valid, vocab_chunk=64)
        )
        assert np.max(lp) <= 1e-5

    def test_untied_lm_head_quantizes(self):
        cfg, params = _tiny("tiny-llama3")
        assert "lm_head" in params
        qp = quantize_params(params)
        assert isinstance(qp["lm_head"], QTensor)
        toks, valid = _batch(cfg, seed=3)
        full = np.asarray(T.token_logprobs(params, cfg, toks, valid))
        quant = np.asarray(T.token_logprobs(qp, cfg, toks, valid))
        assert np.max(np.abs(full - quant)) < 0.1

    def test_topk_agrees_with_full_precision(self):
        cfg, params = _tiny()
        qp = quantize_params(params)
        toks, valid = _batch(cfg, b=8, seed=4)
        keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(8))
        temp, det = jnp.ones(8), jnp.zeros(8, bool)
        ids_f, _ = next_token_topk(
            params, cfg, toks, valid, keys, 5, temp, det, with_gumbel=False
        )
        ids_q, _ = next_token_topk(
            qp, cfg, toks, valid, keys, 5, temp, det, with_gumbel=False
        )
        top1_agree = np.mean(np.asarray(ids_f)[:, 0] == np.asarray(ids_q)[:, 0])
        assert top1_agree >= 0.75

    def test_generate_runs_and_is_deterministic(self):
        cfg, params = _tiny()
        qp = quantize_params(params)
        toks, valid = _batch(cfg, b=2, s=12, seed=5)
        out1 = generate_tokens(
            qp, cfg, toks, valid, jax.random.PRNGKey(9), max_new_tokens=6
        )
        out2 = generate_tokens(
            qp, cfg, toks, valid, jax.random.PRNGKey(9), max_new_tokens=6
        )
        np.testing.assert_array_equal(np.asarray(out1.tokens), np.asarray(out2.tokens))
        assert bool(jnp.all(out1.tokens < cfg.vocab_size))


class TestBackendIntegration:
    @pytest.fixture(scope="class")
    def backends(self):
        kw = dict(model="tiny-gemma2", dtype="float32", max_context=128, base_seed=0)
        return TPUBackend(**kw), TPUBackend(quantization="int8", **kw)

    def test_scores_track_full_precision(self, backends):
        full, quant = backends
        reqs = [
            ScoreRequest(context=f"Context {i} about the issue.", continuation="A fair statement.")
            for i in range(3)
        ]
        lp_f = [r.mean() for r in full.score(reqs)]
        lp_q = [r.mean() for r in quant.score(reqs)]
        np.testing.assert_allclose(lp_q, lp_f, atol=0.1)

    def test_generate_protocol_intact(self, backends):
        _, quant = backends
        results = quant.generate(
            [GenerationRequest(user_prompt="Hello", max_tokens=6, seed=1)]
        )
        assert results[0].finish_reason in ("stop", "length")
        again = quant.generate(
            [GenerationRequest(user_prompt="Hello", max_tokens=6, seed=1)]
        )
        assert results[0].text == again[0].text

    def test_params_bytes_halved(self, backends):
        full, quant = backends
        # int8 weights + f32 scales: comfortably under 60% of f32 bytes
        # for the tiny model (and ~50% of bf16 for production models).
        assert quant._params_bytes < 0.6 * full._params_bytes

    def test_caller_supplied_params_not_invalidated(self):
        """quantization='int8' with a caller-supplied tree must not donate
        the caller's buffers (code-review finding): the tree may be shared
        with another backend or still in use."""
        cfg = get_model_config("tiny-gemma2")
        params = T.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
        TPUBackend(
            model="tiny-gemma2", dtype="float32", max_context=128,
            params=params, quantization="int8",
        )
        # The caller's full-precision arrays are still alive and readable.
        assert np.isfinite(np.asarray(params["embed"])).all()

    def test_token_search_session_consistent_under_int8(self, backends):
        """The fused incremental session and the full-prefix oracle must
        agree on a quantized backend exactly as they do in full precision —
        the int8 weights flow through forward_trunk_tail/forward_shared_trunk
        (sessions) and plain forward (oracle) alike."""
        _, quant = backends
        from consensus_tpu.backends.session import (
            PrefixTokenSearchSession,
            SearchSpec,
        )
        from consensus_tpu.backends.tpu import TPUTokenSearchSession

        spec = SearchSpec(
            ref_system="You draft consensus statements.",
            ref_user="Issue: parks.\nStatement:",
            agent_prompts=(("Agent.", "Opinion: more parks.\nStatement:"),),
            n_slots=2, k=3, temperature=1.0, seed=3, sample=False, max_steps=4,
        )
        fused = TPUTokenSearchSession(quant, spec)
        oracle = PrefixTokenSearchSession(quant, spec)
        try:
            fp = fused.propose()
            op = oracle.propose()
            for slot in range(spec.n_slots):
                assert [c.token_id for c in fp[slot]] == [
                    c.token_id for c in op[slot]
                ]
                np.testing.assert_allclose(
                    [c.ref_logprob for c in fp[slot]],
                    [c.ref_logprob for c in op[slot]],
                    atol=5e-4,
                )
        finally:
            fused.close()
            oracle.close()

    def test_tp_mesh_matches_single_device_under_int8(self):
        """An int8 tree shards over the (data, model) mesh like the
        full-precision one: q slices like the weight, scales replicate on
        their squeezed contraction axis.  Generation must be identical."""
        from consensus_tpu.backends.base import GenerationRequest

        kw = dict(model="tiny-gemma2", dtype="float32", max_context=128,
                  base_seed=0, quantization="int8")
        single = TPUBackend(**kw)
        sharded = TPUBackend(tp=2, **kw)
        reqs = [GenerationRequest(user_prompt="Shard me", max_tokens=6, seed=3)]
        assert single.generate(reqs)[0].text == sharded.generate(reqs)[0].text

    def test_unknown_mode_rejected(self):
        with pytest.raises(ValueError, match="quantization"):
            TPUBackend(model="tiny-gemma2", quantization="int4")
