"""Test harness configuration.

Forces JAX onto the host CPU platform with 8 virtual devices so every
sharding/mesh test runs mesh-shape-faithfully without TPU hardware.

Note: this environment's sitecustomize registers an ``axon`` TPU PJRT
plugin and force-sets ``jax_platforms="axon,cpu"`` via ``jax.config.update``
at interpreter startup — so the env var alone is not enough; we must update
the config back to ``cpu`` after importing jax (backend init is lazy, so
this is safe as long as it happens before the first device lookup).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
