"""Test harness configuration.

Forces JAX onto the host CPU platform with 8 virtual devices so every
sharding/mesh test runs mesh-shape-faithfully without TPU hardware.  Must run
before the first ``import jax`` anywhere in the test session.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
