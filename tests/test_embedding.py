"""Evaluation embedder wiring (VERDICT r2 #6).

The reference embeds with BAAI/bge-large-en-v1.5
(/root/reference/src/utils.py:376-407); this box has zero egress so no bge
checkpoint exists — the default is LM-pooled hiddens and the parity report
must flag cosine metrics as not baseline-comparable.  The
sentence-transformers path is exercised against a tiny ST model BUILT
LOCALLY (transformer module + mean pooling, saved/loaded offline), so the
wiring is proven even though the real encoder isn't fetchable.
"""

import numpy as np
import pytest

from consensus_tpu.backends.fake import FakeBackend
from consensus_tpu.embedding import LMPoolEmbedder, get_embedder


def test_default_is_lm_pool():
    backend = FakeBackend()
    embedder = get_embedder(None, backend)
    assert isinstance(embedder, LMPoolEmbedder)
    assert embedder.name.startswith("lm-pool:")
    vectors = embedder.embed(["a statement", "an opinion"])
    assert vectors.shape[0] == 2


def test_missing_dir_raises():
    with pytest.raises(ValueError, match="not a directory"):
        get_embedder("/nonexistent/bge-large-en-v1.5", FakeBackend())


@pytest.fixture(scope="module")
def tiny_st_dir(tmp_path_factory):
    """Build a tiny sentence-transformers model fully offline: a tiny HF
    BERT + mean pooling, saved in ST format."""
    st = pytest.importorskip("sentence_transformers")
    transformers = pytest.importorskip("transformers")
    from tokenizers import Tokenizer, models as tok_models, pre_tokenizers, trainers

    path = tmp_path_factory.mktemp("tiny_st")
    hf_dir = path / "hf"
    hf_dir.mkdir()

    # Tiny BERT + a word-level tokenizer over a tiny corpus.
    config = transformers.BertConfig(
        vocab_size=200,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=2,
        intermediate_size=64,
        max_position_embeddings=64,
    )
    import torch

    torch.manual_seed(0)
    model = transformers.BertModel(config)
    model.save_pretrained(str(hf_dir))

    tok = Tokenizer(tok_models.WordPiece(unk_token="[UNK]"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    trainer = trainers.WordPieceTrainer(
        vocab_size=200,
        special_tokens=["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"],
    )
    tok.train_from_iterator(
        ["the city should plant more trees", "car free weekends boost shops"],
        trainer,
    )
    tok.save(str(hf_dir / "tokenizer.json"))
    import json

    (hf_dir / "tokenizer_config.json").write_text(
        json.dumps(
            {
                "tokenizer_class": "PreTrainedTokenizerFast",
                "pad_token": "[PAD]",
                "unk_token": "[UNK]",
                "cls_token": "[CLS]",
                "sep_token": "[SEP]",
                "model_max_length": 64,
            }
        )
    )

    from sentence_transformers import SentenceTransformer, models as st_models

    word = st_models.Transformer(str(hf_dir), max_seq_length=32)
    pooling = st_models.Pooling(
        word.get_word_embedding_dimension(), pooling_mode="mean"
    )
    st_model = SentenceTransformer(modules=[word, pooling], device="cpu")
    st_dir = path / "st_model"
    st_model.save(str(st_dir))
    return str(st_dir)


def test_sentence_transformer_embedder_loads_and_embeds(tiny_st_dir):
    embedder = get_embedder(tiny_st_dir, FakeBackend())
    assert embedder.name.startswith("sentence-transformers:")
    vectors = embedder.embed(["plant more trees", "car free weekends"])
    assert vectors.shape == (2, 32)
    np.testing.assert_allclose(
        np.linalg.norm(vectors, axis=1), 1.0, atol=1e-5
    )


def test_evaluator_uses_configured_embedder(tiny_st_dir):
    from consensus_tpu.evaluation import StatementEvaluator

    backend = FakeBackend()
    embedder = get_embedder(tiny_st_dir, backend)
    evaluator = StatementEvaluator(backend, embedder=embedder)
    metrics = evaluator.evaluate_statement(
        "We will plant trees.", "Trees?", {"Agent 1": "yes", "Agent 2": "no"}
    )
    assert "egalitarian_welfare_cosine" in metrics
    # The ST space differs from the LM-pool space: different embedder,
    # different cosine numbers.
    lm_metrics = StatementEvaluator(backend).evaluate_statement(
        "We will plant trees.", "Trees?", {"Agent 1": "yes", "Agent 2": "no"}
    )
    assert (
        metrics["egalitarian_welfare_cosine"]
        != lm_metrics["egalitarian_welfare_cosine"]
    )


def test_parity_report_flags_cosine_incomparability():
    from consensus_tpu.cli.parity_report import build_report, render_markdown

    report = build_report(FakeBackend(), scenarios=[1], weights="fake")
    assert report["cosine_baseline_comparable"] is False
    assert report["embedder"].startswith("lm-pool:")
    markdown = render_markdown(report)
    assert "NOT baseline-comparable" in markdown
    assert "bge-large-en-v1.5" in markdown
