"""Scenario corpus subsystem (PR 18): generator determinism, manifest
integrity, adversarial profile structure, registry refs, and the
corpus-driven load generator."""

import collections
import json
import pathlib

import pytest

from consensus_tpu.data.scenarios import (
    FAMILIES,
    CorpusSpec,
    clear_corpus_cache,
    corpus_root,
    generate_scenarios,
    load_corpus,
    maybe_resolve_scenario,
    parse_family_mix,
    regenerate_check,
    resolve_scenario_ref,
    write_corpus,
)
from consensus_tpu.data.scenarios.corpus import (
    CorpusIntegrityError,
    content_hash,
    family_stats,
    scenarios_blob,
)

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
COMMITTED = REPO_ROOT / "data" / "scenarios_v2"

TINY_SPEC = CorpusSpec(
    version="vtest", seed=7, per_family=2, agent_ladder=(4, 6),
    include_big=False,
)


# ---------------------------------------------------------------------------
# Generator determinism (the property the corpus's versioning rests on)
# ---------------------------------------------------------------------------


class TestDeterminism:
    def test_same_spec_regenerates_byte_identical_jsonl(self):
        blob_a = scenarios_blob(generate_scenarios(TINY_SPEC))
        blob_b = scenarios_blob(generate_scenarios(TINY_SPEC))
        assert blob_a == blob_b
        assert content_hash(blob_a) == content_hash(blob_b)

    def test_seed_and_version_both_partition_the_stream(self):
        base = scenarios_blob(generate_scenarios(TINY_SPEC))
        other_seed = scenarios_blob(generate_scenarios(
            CorpusSpec(version="vtest", seed=8, per_family=2,
                       agent_ladder=(4, 6), include_big=False)))
        other_version = scenarios_blob(generate_scenarios(
            CorpusSpec(version="vtest2", seed=7, per_family=2,
                       agent_ladder=(4, 6), include_big=False)))
        assert base != other_seed
        assert base != other_version

    def test_write_then_check_round_trip(self, tmp_path):
        write_corpus(tmp_path / "c", TINY_SPEC)
        ok, detail = regenerate_check(tmp_path / "c")
        assert ok, detail

    def test_committed_corpus_regenerates_byte_identically(self):
        ok, detail = regenerate_check(COMMITTED)
        assert ok, detail

    def test_tampered_jsonl_fails_verify(self, tmp_path):
        write_corpus(tmp_path / "c", TINY_SPEC)
        jsonl = tmp_path / "c" / "scenarios.jsonl"
        lines = jsonl.read_text().splitlines()
        record = json.loads(lines[0])
        record["issue"] = "Tampered?"
        lines[0] = json.dumps(record, sort_keys=True,
                              separators=(",", ":"))
        jsonl.write_text("\n".join(lines) + "\n")
        with pytest.raises(CorpusIntegrityError):
            load_corpus(tmp_path / "c")


# ---------------------------------------------------------------------------
# Profile structure: the manifest's per-family statistics are true of the
# opinion text itself, not just of the profile metadata.
# ---------------------------------------------------------------------------


class TestProfiles:
    @pytest.fixture(scope="class")
    def corpus(self):
        return load_corpus(COMMITTED)

    def test_manifest_stats_match_recomputation(self, corpus):
        assert family_stats(corpus.scenarios) == corpus.manifest["families"]

    def test_all_families_present(self, corpus):
        assert set(corpus.by_family) == set(FAMILIES)

    def test_agent_counts_span_2_to_500(self, corpus):
        agents = corpus.manifest["agents"]
        assert agents["min"] == 2
        assert agents["max"] == 500
        assert "polarized-500" in corpus.by_id

    def test_polarized_blocs_match_text(self, corpus):
        for s in corpus.by_family["polarized"]:
            counts = collections.Counter(s["agent_opinions"].values())
            assert len(counts) == 2  # exactly two bloc texts
            assert sorted(counts.values(), reverse=True) == sorted(
                s["profile"]["bloc_sizes"], reverse=True)
            assert sum(s["profile"]["bloc_sizes"]) == s["n_agents"]

    def test_holdout_is_a_real_dissenter(self, corpus):
        for s in corpus.by_family["holdout"]:
            holdout = s["profile"]["holdout_agent"]
            counts = collections.Counter(s["agent_opinions"].values())
            if s["n_agents"] == 2:
                assert len(counts) == 2
                continue
            (majority_text, majority_n), = counts.most_common(1)
            assert majority_n == s["n_agents"] - 1
            assert s["agent_opinions"][holdout] != majority_text

    def test_sybil_multiplicity_is_verbatim_duplication(self, corpus):
        for s in corpus.by_family["sybil"]:
            counts = collections.Counter(s["agent_opinions"].values())
            assert max(counts.values()) == s["profile"]["sybil_multiplicity"]
            organic = s["n_agents"] - s["profile"]["sybil_multiplicity"]
            assert s["profile"]["organic"] == organic >= 1

    def test_paraphrase_clusters_share_long_prefixes(self, corpus):
        for s in corpus.by_family["paraphrase"]:
            sizes = s["profile"]["paraphrase_clusters"]
            assert sum(sizes) == s["n_agents"]
            # Cluster members share the whole base opinion as a prefix;
            # group by the first 30 chars and compare the size multiset.
            prefixes = collections.Counter(
                text[:30] for text in s["agent_opinions"].values())
            assert sorted(prefixes.values()) == sorted(sizes)

    def test_contradictory_opinions_contain_both_stances(self, corpus):
        for s in corpus.by_family["contradictory"]:
            assert s["profile"]["incoherent"] == s["n_agents"]


# ---------------------------------------------------------------------------
# Registry refs
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_aamas_ref(self):
        scenario = resolve_scenario_ref("aamas:1")
        assert scenario["issue"]
        assert len(scenario["agent_opinions"]) >= 4

    def test_corpus_ref_by_id(self):
        scenario = resolve_scenario_ref("corpus:v2:polarized-500")
        assert scenario["family"] == "polarized"
        assert scenario["n_agents"] == 500
        assert len(scenario["agent_opinions"]) == 500

    def test_corpus_ref_default_scenario(self):
        scenario = resolve_scenario_ref("corpus:v2")
        first = min(
            load_corpus(COMMITTED).scenarios, key=lambda s: s["id"])
        assert scenario["id"] == first["id"]

    def test_corpus_ref_by_path(self, tmp_path):
        write_corpus(tmp_path / "c", TINY_SPEC)
        clear_corpus_cache()
        scenario = resolve_scenario_ref(f"corpus:{tmp_path / 'c'}:mixed-0001")
        assert scenario["family"] == "mixed"

    @pytest.mark.parametrize("bad", [
        "", "nope:1", "aamas:99", "corpus:", "corpus:v2:no-such-id",
        "corpus:no_such_corpus_name",
    ])
    def test_bad_refs_raise(self, bad):
        with pytest.raises((ValueError, KeyError, FileNotFoundError)):
            resolve_scenario_ref(bad)

    def test_corpus_root_resolves_name(self):
        assert corpus_root("v2") == COMMITTED.resolve()

    def test_maybe_resolve_passthrough_and_override(self):
        inline = {"issue": "X?", "agent_opinions": {"A": "yes"}}
        assert maybe_resolve_scenario(inline) == inline
        resolved = maybe_resolve_scenario(
            {"ref": "corpus:v2:mixed-0000", "issue": "Overridden?"})
        assert resolved["issue"] == "Overridden?"
        assert resolved["agent_opinions"]

    def test_experiment_accepts_scenario_ref_string(self):
        from consensus_tpu.experiment import Experiment

        config = {
            "scenario": "corpus:v2:polarized-0004",
            "methods_to_run": [],
            "models": {},
        }
        experiment = Experiment(config, backend=None)
        assert experiment.issue
        assert len(experiment.agent_opinions) == 13


# ---------------------------------------------------------------------------
# Mix parsing + deterministic sampling
# ---------------------------------------------------------------------------


class TestSampling:
    @pytest.fixture(scope="class")
    def corpus(self):
        return load_corpus(COMMITTED)

    def test_parse_family_mix(self):
        assert parse_family_mix("polarized=2, sybil=1") == {
            "polarized": 2.0, "sybil": 1.0}
        with pytest.raises(ValueError):
            parse_family_mix("polarized")
        with pytest.raises(ValueError):
            parse_family_mix("polarized=0")
        with pytest.raises(ValueError):
            parse_family_mix("")

    def test_round_robin_covers_corpus_in_id_order(self, corpus):
        n = len(corpus.scenarios)
        seq = corpus.sample_sequence(n)
        assert [s["id"] for s in seq] == sorted(corpus.by_id)

    def test_mix_is_deterministic_and_respects_families(self, corpus):
        seq_a = corpus.sample_sequence(
            40, mix="polarized=3,sybil=1", base_seed=5)
        seq_b = corpus.sample_sequence(
            40, mix="polarized=3,sybil=1", base_seed=5)
        assert [s["id"] for s in seq_a] == [s["id"] for s in seq_b]
        families = collections.Counter(s["family"] for s in seq_a)
        assert set(families) <= {"polarized", "sybil"}
        assert families["polarized"] > families["sybil"]

    def test_mix_unknown_family_raises(self, corpus):
        with pytest.raises(ValueError):
            corpus.sample_sequence(4, mix="nonexistent=1")


# ---------------------------------------------------------------------------
# Loadgen integration: corpus payloads + provenance stamping
# ---------------------------------------------------------------------------


class TestLoadgenCorpus:
    def test_corpus_requests_deterministic_with_provenance(self):
        from consensus_tpu.serve.loadgen import corpus_requests

        a = corpus_requests("v2", 12, mix="polarized=1,holdout=1",
                            base_seed=3)
        b = corpus_requests("v2", 12, mix="polarized=1,holdout=1",
                            base_seed=3)
        assert a == b
        assert a.provenance == "corpus:v2:polarized=1,holdout=1"
        assert all(":" in p["request_id"] for p in a)
        # Distinct seeds per request even when scenarios repeat.
        assert len({p["seed"] for p in a}) == 12

    def test_scenario_requests_provenance(self):
        from consensus_tpu.serve.loadgen import scenario_requests

        assert scenario_requests(4).provenance == "round_robin:aamas"
        assert scenario_requests(
            4, scenario_repeat="fixed:2").provenance == "fixed:2"

    def test_report_stamps_scenario_mix(self):
        # run_loadgen against a dead URL: every request fails, but the
        # report must still stamp the workload provenance.
        from consensus_tpu.serve.loadgen import (
            corpus_requests,
            run_loadgen,
        )

        payloads = corpus_requests("v2", 2, base_seed=1)
        report = run_loadgen(
            "http://127.0.0.1:9", payloads, rate_rps=100.0,
            client_timeout_s=0.5,
        )
        assert report["scenario_mix"] == "corpus:v2"
        assert report["completed"] == 0


# ---------------------------------------------------------------------------
# Service-level scenario refs
# ---------------------------------------------------------------------------


class TestServiceRefs:
    def test_parse_request_resolves_corpus_ref(self):
        from consensus_tpu.serve.service import parse_request

        request = parse_request({
            "scenario": "corpus:v2:holdout-0005",
            "method": "best_of_n",
            "params": {"n": 2},
        })
        assert request.issue
        assert len(request.agent_opinions) == 21

    def test_parse_request_rejects_ref_plus_inline(self):
        from consensus_tpu.serve.service import (
            RequestValidationError,
            parse_request,
        )

        with pytest.raises(RequestValidationError) as excinfo:
            parse_request({
                "scenario": "aamas:1",
                "issue": "inline too",
                "method": "best_of_n",
            })
        assert "one or the other" in str(excinfo.value)

    def test_parse_request_rejects_unknown_ref(self):
        from consensus_tpu.serve.service import (
            RequestValidationError,
            parse_request,
        )

        with pytest.raises(RequestValidationError):
            parse_request({
                "scenario": "corpus:v2:definitely-missing",
                "method": "best_of_n",
            })
