"""Durable serving state, end to end (PR 20).

Four proofs, all hardware-free:

* **crash -> replay -> re-ask**: a durable server loses its process with
  a journaled-but-unresolved request; the relaunch replays it through
  normal admission and every re-ask is served byte-identically from the
  idempotency cache (``idempotent_replay``).
* **durability OFF is byte-identical to the PR 19 path**: without
  ``--state-dir`` the scheduler carries no WAL, no durability block, and
  the same seeded request produces the same answer hash.
* **shutdown ordering** (drain -> WAL seal -> blackbox dump) is pinned
  against the SIGTERM regression where the flight recorder dumped a
  half-sealed journal.
* **rolling restart** of a 3-replica elastic fleet: every member cycles
  through drain -> capture -> respawn -> warm-seed with zero aborts, a
  warm PageStore seed on every respawn, and no quarantine flaps; the
  disk spill tier behind it is unit-tested directly.
"""

import dataclasses
import json
import urllib.request

import pytest

from consensus_tpu.obs.metrics import Registry
from consensus_tpu.serve import create_server, parse_request
from consensus_tpu.serve.pagestore import (
    PageStore,
    _content_hash,
    _serialize_run,
)
from consensus_tpu.serve.wal import result_hash

ISSUE = "Should we invest in public transport?"
OPINIONS = {
    "Agent 1": "Yes, buses and trains are vital public goods.",
    "Agent 2": "Only alongside congestion pricing for cars.",
}
PARAMS = {"n": 4, "max_tokens": 24}


def _payload(seed=7, request_id="", **overrides):
    payload = {
        "issue": ISSUE,
        "agent_opinions": OPINIONS,
        "method": "best_of_n",
        "params": dict(PARAMS),
        "seed": seed,
        "evaluate": False,
        "request_id": request_id,
    }
    payload.update(overrides)
    return payload


def _post(base_url, payload, timeout=30.0):
    request = urllib.request.Request(
        base_url + "/v1/consensus",
        data=json.dumps(payload).encode("utf-8"),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(request, timeout=timeout) as response:
        return json.loads(response.read().decode())


def _healthz(base_url):
    with urllib.request.urlopen(base_url + "/healthz", timeout=10.0) as resp:
        return json.loads(resp.read().decode())


def _durable_server(state_dir):
    return create_server(
        backend="fake", port=0, max_inflight=2, max_queue_depth=16,
        registry=Registry(), state_dir=state_dir,
    )


def _wait_for(predicate, timeout_s=20.0, interval_s=0.02):
    import time

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


# ---------------------------------------------------------------------------
# crash -> replay -> idempotent re-ask
# ---------------------------------------------------------------------------


class TestCrashReplay:
    def test_crash_replay_and_idempotent_reask(self, tmp_path):
        # -- life 1: resolve A; leave B journaled-but-unresolved ----------
        life1 = _durable_server(tmp_path).start()
        answer_a = _post(life1.base_url, _payload(seed=11, request_id="A"))
        # B is admitted exactly the way a crash leaves it: an fsync'd
        # `admitted` record with no terminal outcome.  (Appending it
        # directly — rather than racing a SIGKILL against the worker
        # pool — makes the journal state deterministic; the REAL
        # process-death path is scripts/durability_smoke.py.)
        wal = life1.scheduler.wal
        request_b = parse_request(_payload(seed=12, request_id="B"))
        wal.record_admitted("B", None, dataclasses.asdict(request_b))
        wal.close()  # crash: journal left unsealed, lease left on disk
        life1.stop()  # free the socket; seal is a no-op on a closed WAL

        # -- life 2: replay B, then serve every re-ask from the cache ----
        life2 = _durable_server(tmp_path).start()
        try:
            stats = life2.scheduler.wal.stats()
            assert stats["recovered_sealed"] is False
            assert stats["replayed"] == 1
            assert _wait_for(
                lambda: life2.scheduler.wal.stats()["unresolved"] == 0)

            reask_a = _post(life2.base_url, _payload(seed=11,
                                                     request_id="A"))
            assert reask_a["idempotent_replay"] is True
            assert reask_a["statement"] == answer_a["statement"]
            assert result_hash(reask_a) == result_hash(answer_a)

            first_b = _post(life2.base_url, _payload(seed=12,
                                                     request_id="B"))
            second_b = _post(life2.base_url, _payload(seed=12,
                                                      request_id="B"))
            assert first_b["idempotent_replay"] is True  # replay resolved it
            assert second_b["idempotent_replay"] is True
            assert first_b["statement"] == second_b["statement"]

            durability = _healthz(life2.base_url)["durability"]
            assert durability["wal"]["replayed"] == 1
            assert durability["wal"]["unresolved"] == 0
            assert durability["idempotency"]["restored"] >= 1
        finally:
            life2.stop()

        # -- life 3: the clean stop sealed the journal --------------------
        life3 = _durable_server(tmp_path)
        stats = life3.scheduler.wal.stats()
        assert stats["recovered_sealed"] is True
        assert stats["recovered_unresolved"] == 0

    def test_replay_is_byte_identical_to_precrash_answer(self, tmp_path):
        life1 = _durable_server(tmp_path).start()
        original = _post(life1.base_url, _payload(seed=21, request_id="X"))
        life1.scheduler.wal.close()  # crash before the seal
        life1.stop()

        life2 = _durable_server(tmp_path).start()
        try:
            replayed = _post(life2.base_url, _payload(seed=21,
                                                      request_id="X"))
            assert replayed["idempotent_replay"] is True
            assert result_hash(replayed) == result_hash(original)
        finally:
            life2.stop()


# ---------------------------------------------------------------------------
# durability OFF == the PR 19 path
# ---------------------------------------------------------------------------


class TestDurabilityOffByteIdentity:
    def test_no_state_dir_means_no_wal_and_identical_answers(self, tmp_path):
        plain = create_server(
            backend="fake", port=0, max_inflight=2, registry=Registry(),
        ).start()
        try:
            assert plain.scheduler.wal is None
            # request_id pinned: anonymous requests get a process-global
            # server stamp, which would differ between any two servers.
            baseline = _post(plain.base_url, _payload(seed=31,
                                                      request_id="pin-31"))
            health = _healthz(plain.base_url)
            assert "durability" not in health
            assert "durability" not in plain.scheduler.stats()
        finally:
            plain.stop()

        durable = _durable_server(tmp_path).start()
        try:
            answer = _post(durable.base_url, _payload(seed=31,
                                                      request_id="pin-31"))
            assert result_hash(answer) == result_hash(baseline)
            assert "durability" in _healthz(durable.base_url)
        finally:
            durable.stop()


# ---------------------------------------------------------------------------
# shutdown ordering: drain -> WAL seal -> blackbox dump
# ---------------------------------------------------------------------------


class TestShutdownOrdering:
    def test_drain_completes_before_blackbox_dump(self, monkeypatch):
        from consensus_tpu.serve.__main__ import _shutdown

        order = []

        class _Server:
            def stop(self, drain=True):
                assert drain is True
                order.append("drain")

        class _Recorder:
            def dump(self, reason):
                order.append(f"dump:{reason}")

        monkeypatch.setattr(
            "consensus_tpu.obs.trace.get_flight_recorder",
            lambda: _Recorder())
        _shutdown(_Server(), "sigterm")
        assert order == ["drain", "dump:sigterm"]

    def test_clean_exit_drains_without_dumping(self, monkeypatch):
        from consensus_tpu.serve.__main__ import _shutdown

        order = []

        class _Server:
            def stop(self, drain=True):
                order.append("drain")

        class _Recorder:
            def dump(self, reason):  # pragma: no cover - the regression
                order.append("dump")

        monkeypatch.setattr(
            "consensus_tpu.obs.trace.get_flight_recorder",
            lambda: _Recorder())
        _shutdown(_Server(), "exit")
        assert order == ["drain"]


# ---------------------------------------------------------------------------
# rolling restart: zero-loss fleet cycling with warm seeds
# ---------------------------------------------------------------------------


class TestRollingRestart:
    def test_three_replica_fleet_cycles_with_warm_seeds(self, tmp_path):
        registry = Registry()
        server = create_server(
            backend="fake", port=0, registry=registry,
            max_inflight=2, max_queue_depth=16,
            fleet_size=3,
            fleet_options={
                "elastic": True,
                "elastic_options": {"check_interval_s": 0.05,
                                    "respawn_backoff_s": 0.05,
                                    "harvest_interval_s": 0.05},
            },
            engine=True,
            engine_options={"prefix_cache": True},
            state_dir=tmp_path,
        ).start()
        router = server.scheduler
        manager = router.manager
        try:
            # Warm the prefix caches (and therefore the harvested
            # PageStore) with a few scenario-repeating requests.
            baseline = {}
            for seed in (41, 42, 43, 44):
                baseline[seed] = _post(
                    server.base_url, _payload(seed=seed))["statement"]
            assert _wait_for(
                lambda: (manager.snapshot()["page_store"] or {}).get(
                    "runs", 0) > 0)

            result = manager.rolling_restart()
            assert result["aborted"] is None
            assert sorted(result["restarted"]) == ["r0", "r1", "r2"]

            snap = manager.snapshot()
            assert snap["restarts"] == 3
            assert snap["quarantined"] == {}  # a restart is not a flap
            # Acceptance: warm-seed hit on EVERY respawned replica.
            for name in ("r0", "r1", "r2"):
                assert snap["warm_seeded"].get(name, 0) > 0, name
            for event in snap["restart_events"]:
                assert event["completed_s"] >= event["started_s"]
                assert event["warm_seeded"] > 0

            # The restarted fleet serves byte-identically.
            for seed, statement in baseline.items():
                assert _post(
                    server.base_url,
                    _payload(seed=seed))["statement"] == statement
            # The spill tier persisted runs on disk for the NEXT process.
            assert list((tmp_path / "pages").glob("*.run"))
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# disk-backed PageStore spill tier
# ---------------------------------------------------------------------------


def _run_blob(token, identity=("tier", "fp32", 1), page_size=4):
    tokens = tuple(range(token, token + 8))
    run = {
        "identity": identity,
        "key": bytes([token % 256]) * 8,
        "tokens": tokens,
        "n_tokens": len(tokens),
        "page_size": page_size,
        "n_pages": 2,
        "payload": bytes([token % 256]) * 64,
    }
    blob = _serialize_run(run)
    return run, blob, _content_hash(blob)


class TestPageStoreDiskTier:
    def test_admissions_spill_and_reindex_across_restart(self, tmp_path):
        store = PageStore(registry=Registry(), spill_dir=tmp_path)
        _, blob, blob_hash = _run_blob(1)
        store.admit_blob(blob, blob_hash)
        assert (tmp_path / f"{blob_hash}.run").exists()

        # A NEW store over the same dir re-indexes lazily (nothing in
        # memory) and restores the run — hash-verified — at first fetch.
        reborn = PageStore(registry=Registry(), spill_dir=tmp_path)
        stats = reborn.stats()
        assert stats["disk"]["runs"] == 1
        assert stats["runs"] == 0
        client = reborn.client("test")
        listing = client._call("fetch", {"phase": "list"})
        assert listing["ok"] and len(listing["runs"]) == 1
        fetched = client._fetch_blob(listing["runs"][0])
        assert fetched == blob
        assert reborn.stats()["disk"]["restored"] == 1

    def test_corrupt_spill_file_is_refused_at_index(self, tmp_path):
        store = PageStore(registry=Registry(), spill_dir=tmp_path)
        _, blob, blob_hash = _run_blob(2)
        store.admit_blob(blob, blob_hash)
        path = tmp_path / f"{blob_hash}.run"
        path.write_bytes(blob[:-1] + b"\x00")  # bit rot

        reborn = PageStore(registry=Registry(), spill_dir=tmp_path)
        assert reborn.stats()["disk"]["runs"] == 0
        assert not path.exists()  # refused AND removed

    def test_disk_budget_evicts_lru(self, tmp_path):
        _, blob, _ = _run_blob(3)
        store = PageStore(
            registry=Registry(), spill_dir=tmp_path,
            disk_budget_bytes=2 * len(blob) + 1,
        )
        hashes = []
        for token in (3, 4, 5):
            _, blob, blob_hash = _run_blob(token)
            store.admit_blob(blob, blob_hash)
            hashes.append(blob_hash)
        stats = store.stats()["disk"]
        assert stats["evicted"] >= 1
        assert not (tmp_path / f"{hashes[0]}.run").exists()  # oldest out
        assert (tmp_path / f"{hashes[-1]}.run").exists()

    def test_memory_eviction_keeps_disk_files(self, tmp_path):
        store = PageStore(
            max_runs=1, registry=Registry(), spill_dir=tmp_path)
        for token in (6, 7):
            _, blob, blob_hash = _run_blob(token)
            store.admit_blob(blob, blob_hash)
        stats = store.stats()
        assert stats["runs"] == 1  # memory LRU evicted the first
        assert stats["disk"]["runs"] == 2  # disk kept both
