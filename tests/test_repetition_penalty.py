"""Repetition penalty: HF/Together semantics on the TPU decode paths.

The reference forwards a ``repetition_penalty`` param to the Together API
(src/utils.py:88,156,184; finite_lookahead.py:332 passes 1.0) — parity
requires honoring it when set.  On device it is a presence-masked logit
transform inside the decode loop (models/sampling.apply_repetition_penalty)
with the seen-token mask seeded from the prompt and updated per step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensus_tpu.backends.base import GenerationRequest
from consensus_tpu.backends.tpu import TPUBackend
from consensus_tpu.models.config import get_model_config
from consensus_tpu.models.generate import (
    generate_tokens,
    generate_tokens_segmented,
    generate_tokens_shared_trunk,
    generate_tokens_shared_trunk_segmented,
)
from consensus_tpu.models.sampling import apply_repetition_penalty
from consensus_tpu.models.transformer import init_params

BATCH = 4
CTX = 32
MAX_NEW = 64
SEG = 16


def test_penalty_math():
    """Seen positive logits divide by the penalty, seen negative multiply;
    unseen logits are untouched."""
    logits = jnp.asarray([[2.0, -2.0, 1.0, -1.0]])
    presence = jnp.asarray([[True, True, False, False]])
    out = np.asarray(
        apply_repetition_penalty(logits, presence, jnp.asarray([2.0]))
    )
    np.testing.assert_allclose(out, [[1.0, -4.0, 1.0, -1.0]])


@pytest.fixture(scope="module")
def setup():
    config = get_model_config("tiny-gemma2", vocab_size=128)
    params = init_params(config, jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (1, CTX), 1, config.vocab_size, jnp.int32
    )
    valid = jnp.ones((1, CTX), bool)
    keys = jax.vmap(lambda i: jax.random.fold_in(jax.random.PRNGKey(7), i))(
        jnp.arange(BATCH)
    )
    return config, params, prompt, valid, keys


def _repeat_fraction(tokens: np.ndarray) -> float:
    """Mean fraction of steps that emit an already-emitted token."""
    fracs = []
    for row in tokens:
        seen, repeats = set(), 0
        for tok in row:
            repeats += tok in seen
            seen.add(tok)
        fracs.append(repeats / max(len(row), 1))
    return float(np.mean(fracs))


def test_penalty_reduces_repeats_and_paths_agree(setup):
    """A strong penalty measurably cuts token repetition on a greedy
    decode (random tiny models loop hard without it), and the monolithic
    and segmented paths implement identical penalty semantics."""
    config, params, prompt, valid, keys = setup
    common = dict(
        batch=BATCH, key=keys, max_new_tokens=MAX_NEW, pad_id=0,
        temperature=jnp.zeros((BATCH,), jnp.float32),  # greedy
    )
    plain = generate_tokens_shared_trunk(
        params, config, prompt, valid, **common
    )
    rp = jnp.full((BATCH,), 8.0, jnp.float32)
    mono = generate_tokens_shared_trunk(
        params, config, prompt, valid, rep_penalty=rp, **common
    )
    seg = generate_tokens_shared_trunk_segmented(
        params, config, prompt, valid, seg_len=SEG, rep_penalty=rp, **common
    )
    np.testing.assert_array_equal(np.asarray(mono.tokens), np.asarray(seg.tokens))
    assert _repeat_fraction(np.asarray(mono.tokens)) < _repeat_fraction(
        np.asarray(plain.tokens)
    )


def test_classic_paths_agree(setup):
    config, params, prompt, valid, keys = setup
    prompts = jnp.tile(prompt, (BATCH, 1))
    valids = jnp.tile(valid, (BATCH, 1))
    rp = jnp.full((BATCH,), 4.0, jnp.float32)
    common = dict(
        key=keys, max_new_tokens=MAX_NEW, pad_id=0,
        temperature=jnp.ones((BATCH,), jnp.float32),
        rep_penalty=rp,
    )
    mono = generate_tokens(params, config, prompts, valids, **common)
    seg = generate_tokens_segmented(
        params, config, prompts, valids, seg_len=SEG, **common
    )
    np.testing.assert_array_equal(np.asarray(mono.tokens), np.asarray(seg.tokens))


def test_backend_accepts_repetition_penalty():
    backend = TPUBackend(
        model="tiny-gemma2", max_context=64, base_seed=0, dtype="float32",
        decode_segment_len=32,
    )
    requests = [
        GenerationRequest(
            user_prompt="Draft prompt.", max_tokens=70, seed=3 + i,
            temperature=1.0, repetition_penalty=1.3,
        )
        for i in range(4)
    ]
    results = backend.generate(requests)
    assert all(r.ok for r in results)
    # Penalty-free requests keep rep_penalty out of the decode kwargs
    # entirely (no new compiled program variants on the default path).
    *_, rep = backend._prep_generation_rows(
        [GenerationRequest(user_prompt="x", max_tokens=8)], allowed=8
    )
    assert rep is None
