"""Pure data-parallel multi-chip SERVING (SURVEY §2.16 table, §5.8).

The production multi-chip mode for the 2B/9B models is tp=1, dp=N: params
replicated, protocol batch rows sharded over the ``data`` mesh axis.  These
tests prove on the 8-virtual-device CPU mesh that a dp=8 backend returns
per-row results identical to the single-device backend — per-request PRNG
keys make results independent of batch composition AND of device layout.
"""

import numpy as np
import pytest

from consensus_tpu.backends.base import (
    GenerationRequest,
    NextTokenRequest,
    ScoreRequest,
)
from consensus_tpu.backends.tpu import TPUBackend


@pytest.fixture(scope="module")
def single():
    return TPUBackend(model="tiny-gemma2", max_context=128, base_seed=7)


@pytest.fixture(scope="module")
def dp8():
    backend = TPUBackend(model="tiny-gemma2", max_context=128, base_seed=7, dp=8)
    assert backend.mesh_plan is not None
    assert backend.mesh_plan.dp == 8 and backend.mesh_plan.tp == 1
    return backend


PROMPTS = [f"Opinion {i}: the city should plant more trees." for i in range(12)]


def test_dp_generate_matches_single_device(single, dp8):
    requests = [
        GenerationRequest(user_prompt=p, max_tokens=8, seed=100 + i, temperature=0.7)
        for i, p in enumerate(PROMPTS)
    ]
    ours = dp8.generate(requests)
    ref = single.generate(requests)
    assert [r.token_ids for r in ours] == [r.token_ids for r in ref]


def test_dp_segmented_generate_matches_single_device(single, dp8):
    """Long budgets route through the segmented decode (host loop over
    _decode_segment with frozen KV operands passed between jits) — per-row
    results must stay identical at dp=8, sharded or replicated."""
    requests = [
        GenerationRequest(
            # Identical prompts -> shared-trunk segmented; 200 buckets to
            # 256, which segments (2x128) at the backend default ladder.
            user_prompt="One shared draft prompt for the whole cell.",
            max_tokens=200,
            seed=300 + i,
            temperature=1.0,
        )
        for i in range(8)
    ]
    for backend in (single, dp8):
        assert backend._seg_len_for(256) is not None
    ours = dp8.generate(requests)
    ref = single.generate(requests)
    assert [r.token_ids for r in ours] == [r.token_ids for r in ref]


def test_dp_score_matches_single_device(single, dp8):
    requests = [
        ScoreRequest(context=f"Agent {i} believes trees matter.", continuation=p)
        for i, p in enumerate(PROMPTS)
    ]
    ours = dp8.score(requests)
    ref = single.score(requests)
    for a, b in zip(ours, ref):
        assert a.tokens == b.tokens
        np.testing.assert_allclose(a.logprobs, b.logprobs, atol=1e-5, rtol=1e-5)


def test_dp_next_token_matches_single_device(single, dp8):
    requests = [
        NextTokenRequest(user_prompt=p, k=4, seed=i, temperature=0.8)
        for i, p in enumerate(PROMPTS)
    ]
    ours = dp8.next_token_logprobs(requests)
    ref = single.next_token_logprobs(requests)
    for a, b in zip(ours, ref):
        assert [c.token_id for c in a] == [c.token_id for c in b]


def test_dp_embed_matches_single_device(single, dp8):
    ours = dp8.embed(PROMPTS)
    ref = single.embed(PROMPTS)
    np.testing.assert_allclose(ours, ref, atol=1e-5, rtol=1e-5)


def test_dp_session_matches_single_device(single, dp8):
    """Token-search sessions on a DP backend: rows may not divide dp (role
    counts are odd); the batch then stays uncommitted/replicated — a full
    propose/advance step must run and match the single-device session."""
    from consensus_tpu.backends.session import SearchSpec, open_token_search

    spec = SearchSpec(
        ref_system="You draft consensus statements.",
        ref_user="Issue: trees.\nStatement:",
        agent_prompts=(
            ("Agent context.", "Opinion: plant more.\nStatement:"),
            ("Agent context.", "Opinion: too costly.\nStatement:"),
        ),
        n_slots=2,
        k=3,
        temperature=1.0,
        seed=11,
        sample=False,
        max_steps=4,
    )
    s_dp = open_token_search(dp8, spec)
    s_ref = open_token_search(single, spec)
    try:
        props_dp = s_dp.propose()
        props_ref = s_ref.propose()
        ids_dp = [[c.token_id for c in slot] for slot in props_dp]
        ids_ref = [[c.token_id for c in slot] for slot in props_ref]
        assert ids_dp == ids_ref
        chosen = [props_dp[0][0], props_dp[1][1]]
        next_dp = s_dp.advance_and_propose([0, 1], chosen)
        next_ref = s_ref.advance_and_propose([0, 1], [props_ref[0][0], props_ref[1][1]])
        assert [[c.token_id for c in slot] for slot in next_dp] == [
            [c.token_id for c in slot] for slot in next_ref
        ]
    finally:
        s_dp.close()
        s_ref.close()


def test_dp_welfare_pipeline_matches_single_device(single, dp8):
    """End-to-end best_of_n under dp=8 equals the single-device run — the
    statement picked, not just the tensors."""
    from consensus_tpu.methods import get_method_generator

    config = {"n": 4, "max_tokens": 8, "seed": 3, "temperature": 0.9}
    issue = "Should the city center be car-free?"
    opinions = {"Agent 1": "Yes, cleaner air.", "Agent 2": "No, deliveries."}

    gen_dp = get_method_generator("best_of_n", dp8, config, "tiny-gemma2")
    gen_single = get_method_generator("best_of_n", single, config, "tiny-gemma2")
    assert gen_dp.generate_statement(issue, opinions) == gen_single.generate_statement(
        issue, opinions
    )


def test_dp_composes_with_int8(single):
    """Pure-DP serving with the production int8 weights: dp=8 results equal
    the single-device bf16-path backend only in structure (different
    quantization), so compare against a single-device int8 backend."""
    int8_single = TPUBackend(
        model="tiny-gemma2", max_context=128, base_seed=7, quantization="int8"
    )
    int8_dp = TPUBackend(
        model="tiny-gemma2", max_context=128, base_seed=7, quantization="int8",
        dp=8,
    )
    requests = [
        GenerationRequest(user_prompt=p, max_tokens=6, seed=200 + i)
        for i, p in enumerate(PROMPTS[:8])
    ]
    ours = int8_dp.generate(requests)
    ref = int8_single.generate(requests)
    assert [r.token_ids for r in ours] == [r.token_ids for r in ref]

    scores_dp = int8_dp.score(
        [ScoreRequest(context="ctx", continuation=p) for p in PROMPTS[:8]]
    )
    scores_ref = int8_single.score(
        [ScoreRequest(context="ctx", continuation=p) for p in PROMPTS[:8]]
    )
    for a, b in zip(scores_dp, scores_ref):
        np.testing.assert_allclose(a.logprobs, b.logprobs, atol=1e-5, rtol=1e-5)
