"""Agent-parallel utility-matrix scoring (PR 10).

The contract pinned here:

* the fallback seam is BYTE-identical to the per-call code it replaces —
  same ScoreRequest rows, same reduction expressions, same float64
  values, same pinned (numpy first-max) argmax;
* consumers gate on ``matrix_scoring`` (default ON) and produce
  byte-identical statements/metrics with the seam on or off, across
  seeds, on the fake backend (best-of-N, beam search, the evaluator);
* merged score dispatches dedup identical rows (engine and legacy
  flush) and count removals in ``engine_score_dedup_total``;
* the fused TPU path agrees with the fallback to float tolerance with
  the same argmax, on BOTH tiny model families and every stat;
* a 64-agent matrix streams in chunks under a shrunken HBM session
  budget without falling back, bit-identical to the unchunked run;
* dp=4 and dp=1 produce identical utilities (8 virtual CPU devices
  from conftest.py).
"""

import numpy as np
import pytest

from consensus_tpu.backends.base import PartialBatchError, ScoreRequest
from consensus_tpu.backends.batching import BatchingBackend
from consensus_tpu.backends.fake import FakeBackend
from consensus_tpu.backends.score_matrix import (
    AgentContext,
    ScoreMatrixRequest,
    dedup_score_requests,
    expand_deduped,
    expand_partial_error,
    fallback_score_matrix_many,
    score_matrix_many,
    welfare_argmax,
)
from consensus_tpu.obs.metrics import Registry

ISSUE = "Should the city build more parks or more parking?"
OPINIONS = {
    "alice": "Parks improve health and community.",
    "bob": "Parking shortages strangle local business.",
    "carol": "Both matter; phase the spending.",
}


def _family_total(registry, name):
    family = (registry.snapshot().get("families") or {}).get(name) or {}
    return sum(s.get("value", 0) for s in family.get("series", []))


# ---------------------------------------------------------------------------
# The seam itself
# ---------------------------------------------------------------------------


class TestSeam:
    def _request(self, stat="mean"):
        return ScoreMatrixRequest(
            agents=(
                AgentContext(context="ctx a", chat=False),
                AgentContext(context="ctx b", chat=False),
            ),
            candidates=("one", "two", "three"),
            stat=stat,
        )

    def test_cell_requests_candidate_major(self):
        rows = self._request().cell_requests()
        assert [(r.context, r.continuation) for r in rows] == [
            ("ctx a", "one"), ("ctx b", "one"),
            ("ctx a", "two"), ("ctx b", "two"),
            ("ctx a", "three"), ("ctx b", "three"),
        ]

    def test_bad_stat_and_rule_rejected(self):
        with pytest.raises(ValueError):
            self._request(stat="median")
        with pytest.raises(ValueError):
            ScoreMatrixRequest(
                agents=(AgentContext(context="c"),),
                candidates=("x",),
                welfare_rule="plutocratic",
            )

    def test_fallback_matches_percall_expressions(self):
        """Every stat reduces exactly as the consumer it serves did."""
        backend = FakeBackend()
        request = self._request()
        results = backend.score(request.cell_requests())
        for stat, expect in (
            ("mean", [r.mean(default=-10.0) for r in results]),
            ("sum", [float(sum(r.logprobs)) for r in results]),
            ("last", [float(r.logprobs[-1]) for r in results]),
        ):
            matrix = fallback_score_matrix_many(
                backend, [self._request(stat=stat)]
            )[0]
            assert matrix.utilities.ravel().tolist() == expect
        moments = fallback_score_matrix_many(
            backend, [self._request(stat="moments")]
        )[0]
        for cell_lp, cell_p, r in zip(
            moments.utilities.ravel(), moments.aux.ravel(), results
        ):
            lps = np.asarray(r.logprobs, dtype=np.float64)
            assert cell_lp == float(lps.mean())
            assert cell_p == float(np.exp(lps).mean())

    def test_welfare_argmax_pins_first_max(self):
        utilities = np.asarray([[1.0, 5.0], [2.0, 1.0], [1.0, 2.0]])
        welfare, best = welfare_argmax(utilities, "egalitarian")
        assert welfare.tolist() == [1.0, 1.0, 1.0]
        assert best == 0  # first max, numpy semantics

    def test_empty_matrix(self):
        request = ScoreMatrixRequest(agents=(), candidates=())
        result = fallback_score_matrix_many(FakeBackend(), [request])[0]
        assert result.utilities.shape == (0, 0)
        assert result.best == 0

    def test_dedup_mapping_roundtrip(self):
        a = ScoreRequest(context="x", continuation="1", chat=False)
        b = ScoreRequest(context="y", continuation="2", chat=False)
        unique, mapping = dedup_score_requests([a, b, a, a, b])
        assert len(unique) == 2
        assert expand_deduped(["A", "B"], mapping) == ["A", "B", "A", "A", "B"]

    def test_expand_partial_error(self):
        a = ScoreRequest(context="x", continuation="1", chat=False)
        b = ScoreRequest(context="y", continuation="2", chat=False)
        _, mapping = dedup_score_requests([a, b, a])
        error = PartialBatchError("boom", ["ra", None], {1: "bad row"})
        expanded = expand_partial_error(error, mapping)
        assert expanded.results == ["ra", None, "ra"]
        assert expanded.row_errors == {1: "bad row"}

    def test_obs_families_recorded(self):
        registry = Registry()
        from consensus_tpu.backends.score_matrix import record_matrix

        result = fallback_score_matrix_many(FakeBackend(), [self._request()])[0]
        record_matrix(result, 2, registry)
        assert _family_total(registry, "score_matrix_cells_total") == 6
        assert _family_total(registry, "score_matrix_d2h_bytes_total") > 0
        fam = (registry.snapshot().get("families") or {}).get(
            "score_agents_per_call"
        )
        assert fam is not None


# ---------------------------------------------------------------------------
# Consumer byte-identity (fake backend), matrix on vs off
# ---------------------------------------------------------------------------


class TestConsumerIdentity:
    @pytest.mark.parametrize("method", ["best_of_n", "beam_search"])
    @pytest.mark.parametrize("seed", [0, 7, 123])
    def test_statements_identical(self, method, seed):
        from consensus_tpu.methods import get_method_generator

        texts = {}
        for matrix_on in (True, False):
            generator = get_method_generator(
                method,
                FakeBackend(),
                {"n": 4, "max_tokens": 12, "seed": seed, "beam_width": 3,
                 "matrix_scoring": matrix_on},
            )
            texts[matrix_on] = generator.generate_statement(ISSUE, OPINIONS)
        assert texts[True] == texts[False]

    def test_evaluator_metrics_identical(self):
        from consensus_tpu.evaluation import StatementEvaluator

        statements = ["Fund both.", "Parks first.", "Fund both."]
        rows = {}
        for matrix_on in (True, False):
            rows[matrix_on] = StatementEvaluator(
                FakeBackend(), matrix_scoring=matrix_on
            ).evaluate_statements_batched(statements, ISSUE, OPINIONS)
        for on, off in zip(rows[True], rows[False]):
            assert set(on) == set(off)
            for key in on:
                assert on[key] == off[key], key

    def test_best_of_n_utilities_float32_cast_stable(self):
        """best-of-N historically built an f32 matrix; the float64
        fallback utilities must cast to the identical f32 values."""
        from consensus_tpu.methods.best_of_n import BestOfNGenerator

        backend = FakeBackend()
        candidates = ["Fund both now.", "Parks first."]
        on = BestOfNGenerator(
            backend, {"matrix_scoring": True}
        ).score_candidates(ISSUE, OPINIONS, candidates)
        off = BestOfNGenerator(
            backend, {"matrix_scoring": False}
        ).score_candidates(ISSUE, OPINIONS, candidates)
        assert on.dtype == off.dtype == np.float32
        assert np.array_equal(on, off)


# ---------------------------------------------------------------------------
# Dispatch seams: engine + legacy flush, dedup accounting
# ---------------------------------------------------------------------------


class TestDispatch:
    def _request(self):
        return ScoreMatrixRequest(
            agents=(
                AgentContext(context="ctx a", chat=False),
                AgentContext(context="ctx b", chat=False),
            ),
            candidates=("one", "two"),
        )

    @pytest.mark.parametrize("engine", [True, False])
    def test_batching_score_matrix_matches_direct(self, engine):
        direct = fallback_score_matrix_many(FakeBackend(), [self._request()])[0]
        batching = BatchingBackend(
            FakeBackend(), registry=Registry(), engine=engine
        )
        try:
            with batching.session():
                via = score_matrix_many(batching, [self._request()])[0]
        finally:
            batching.close()
        assert np.array_equal(via.utilities, direct.utilities)
        assert via.best == direct.best

    @pytest.mark.parametrize("engine", [True, False])
    def test_score_dedup_counter(self, engine):
        registry = Registry()
        batching = BatchingBackend(
            FakeBackend(), registry=registry, engine=engine
        )
        try:
            duplicate = ScoreRequest(
                context="same ctx", continuation="same cont", chat=False
            )
            with batching.session():
                results = batching.score(
                    [duplicate, duplicate,
                     ScoreRequest(context="other", continuation="x",
                                  chat=False)]
                )
            assert results[0].logprobs == results[1].logprobs
        finally:
            batching.close()
        assert _family_total(registry, "engine_score_dedup_total") >= 1


# ---------------------------------------------------------------------------
# Fused device path (tiny real models)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_backends():
    from consensus_tpu.backends.tpu import TPUBackend

    return {
        model: TPUBackend(model=model, dtype="float32", max_context=256)
        for model in ("tiny-gemma2", "tiny-llama3")
    }


def _tiny_request(n_agents=3, n_candidates=3, stat="mean"):
    return ScoreMatrixRequest(
        agents=tuple(
            AgentContext(
                context=f"Opinion holder {i} wants more of option {i}.",
                system_prompt="You are a panelist.",
                chat=True,
            )
            for i in range(n_agents)
        ),
        candidates=tuple(
            f"Candidate statement {j} about the issue." for j in range(n_candidates)
        ),
        stat=stat,
    )


class TestFusedParity:
    @pytest.mark.parametrize("model", ["tiny-gemma2", "tiny-llama3"])
    @pytest.mark.parametrize("stat", ["mean", "sum", "last", "moments"])
    def test_fused_matches_fallback(self, tiny_backends, model, stat):
        backend = tiny_backends[model]
        request = _tiny_request(stat=stat)
        fused = backend.score_matrix([request])[0]
        assert fused.path == "fused"
        fallback = fallback_score_matrix_many(backend, [request])[0]
        np.testing.assert_allclose(
            np.asarray(fused.utilities, np.float64),
            fallback.utilities,
            atol=5e-5, rtol=5e-5,
        )
        assert fused.best == fallback.best
        np.testing.assert_allclose(
            np.asarray(fused.welfare, np.float64),
            np.asarray(fallback.welfare, np.float64),
            atol=5e-5, rtol=5e-5,
        )
        if stat == "moments":
            np.testing.assert_allclose(
                np.asarray(fused.aux, np.float64), fallback.aux,
                atol=5e-5, rtol=5e-5,
            )

    def test_d2h_is_reductions_only(self, tiny_backends):
        """The fused path ships (C, A) + (C,) floats — never the per-token
        logprob vectors the fallback reports."""
        backend = tiny_backends["tiny-gemma2"]
        request = _tiny_request()
        fused = backend.score_matrix([request])[0]
        fallback = fallback_score_matrix_many(backend, [request])[0]
        n_cells = len(request.agents) * len(request.candidates)
        assert fused.d2h_bytes == n_cells * 4 + len(request.candidates) * 4
        assert fallback.d2h_bytes > 10 * fused.d2h_bytes

    def test_overlong_rows_fall_back(self, tiny_backends):
        """Rows needing the per-call scorer's truncation semantics route
        the whole request through it."""
        backend = tiny_backends["tiny-gemma2"]
        request = ScoreMatrixRequest(
            agents=(
                AgentContext(context="word " * 400, chat=False),
            ),
            candidates=("short tail.",),
        )
        before = backend.matrix_stats["fallbacks"]
        result = backend.score_matrix([request])[0]
        assert result.path == "fallback"
        assert backend.matrix_stats["fallbacks"] == before + 1

    def test_64_agents_chunk_under_budget(self, tiny_backends):
        """The acceptance case: a 64-agent matrix streams through a
        shrunken HBM session budget in >1 chunk, no fallback, and the
        chunked utilities are bit-identical to the unchunked run."""
        backend = tiny_backends["tiny-gemma2"]
        request = ScoreMatrixRequest(
            agents=tuple(
                AgentContext(
                    context=f"Panel member {i} holds position variant {i}.",
                    chat=True,
                )
                for i in range(64)
            ),
            candidates=("Fund parks first.", "Parking is essential."),
        )
        full = backend.score_matrix([request])[0]
        assert full.path == "fused"

        config = backend.config
        page_bytes = (
            config.n_layers * 16 * config.n_kv_heads * config.head_dim * 4 * 2
        )
        # Recompute the fused layout's shared-page total so the shrunken
        # budget leaves room for the shared pages plus only ~8 rows of
        # private tail pages — forcing the 128-row batch to chunk.
        shared_pages = 0
        for agent in request.agents:
            ids = backend.tokenizer.encode(
                backend._score_prefix(agent.to_score_request("")),
                add_bos=True,
            )
            shared_pages += ((len(ids) - 1) // 16 * 16) // 16
        cap = backend._session_budget.cap
        backend._session_budget.cap = page_bytes * (shared_pages + 8 * 8 + 1)
        chunks_before = backend.matrix_stats["chunks"]
        fallbacks_before = backend.matrix_stats["fallbacks"]
        try:
            chunked = backend.score_matrix([request])[0]
        finally:
            backend._session_budget.cap = cap
        assert chunked.path == "fused"
        assert backend.matrix_stats["fallbacks"] == fallbacks_before
        assert backend.matrix_stats["chunks"] - chunks_before > 1
        assert np.array_equal(
            np.asarray(chunked.utilities), np.asarray(full.utilities)
        )

    def test_dp4_matches_dp1(self, tiny_backends):
        """Sharding the row batch over the dp mesh must not change the
        utilities (8 virtual CPU devices from conftest)."""
        from consensus_tpu.backends.tpu import TPUBackend

        base = tiny_backends["tiny-gemma2"]
        wide = TPUBackend(
            model="tiny-gemma2", dtype="float32", max_context=256, dp=4,
            params=base.params, config=base.config,
        )
        request = _tiny_request(n_agents=8, n_candidates=4)
        r1 = base.score_matrix([request])[0]
        r4 = wide.score_matrix([request])[0]
        assert r1.path == r4.path == "fused"
        assert np.array_equal(
            np.asarray(r1.utilities), np.asarray(r4.utilities)
        )
        assert r1.best == r4.best

    def test_token_accounting(self, tiny_backends):
        backend = tiny_backends["tiny-gemma2"]
        request = _tiny_request()
        before = backend.token_counts["scored"]
        backend.score_matrix([request])
        scored = backend.token_counts["scored"] - before
        cont_tokens = sum(
            len(backend.tokenizer.encode(c)) for c in request.candidates
        )
        assert scored == len(request.agents) * cont_tokens


# ---------------------------------------------------------------------------
# Loadgen many-agent expansion (satellite 6)
# ---------------------------------------------------------------------------


class TestLoadgenAgents:
    def test_expansion_deterministic_and_sized(self):
        from consensus_tpu.serve.loadgen import scenario_requests

        payloads = scenario_requests(3, agents=64)
        assert all(len(p["agent_opinions"]) == 64 for p in payloads)
        again = scenario_requests(3, agents=64)
        assert [p["agent_opinions"] for p in payloads] == [
            p["agent_opinions"] for p in again
        ]
        # Variant copies are textually distinct from their base opinion.
        opinions = payloads[0]["agent_opinions"]
        names = list(opinions)
        assert any("_v" in n for n in names)
        base = {n: o for n, o in opinions.items() if "_v" not in n}
        for name, text in opinions.items():
            if "_v" in name:
                assert text not in base.values()

    def test_truncation_below_base_count(self):
        from consensus_tpu.serve.loadgen import scenario_requests

        payloads = scenario_requests(1, agents=2)
        assert len(payloads[0]["agent_opinions"]) == 2
