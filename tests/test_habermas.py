"""End-to-end Habermas Machine tests on the deterministic fake backend —
coverage the reference never had above its pure Schulze/parsing functions
(SURVEY §4)."""

import numpy as np
import pytest

from consensus_tpu.backends.fake import FakeBackend
from consensus_tpu.methods import get_method_generator

ISSUE = "How should the city reduce traffic congestion?"
OPINIONS = {
    "Agent 1": "Expand public transport and make it cheaper.",
    "Agent 2": "Build more roads; driving is essential for families.",
    "Agent 3": "Congestion pricing works; charge drivers at peak hours.",
    "Agent 4": "Remote work incentives would cut traffic at the source.",
}


@pytest.fixture()
def backend():
    return FakeBackend()


def make_gen(backend, **cfg):
    base = {"num_candidates": 3, "num_rounds": 1, "seed": 42, "max_tokens": 200}
    base.update(cfg)
    return get_method_generator("habermas_machine", backend, base)


def test_end_to_end_produces_statement(backend):
    gen = make_gen(backend)
    statement = gen.generate_statement(ISSUE, OPINIONS)
    assert statement and not statement.startswith("[ERROR")
    # Intermediate state is retained for inspection.
    assert len(gen.candidate_statements) == 3
    assert set(gen.agent_rankings) == set(OPINIONS)
    assert len(gen.all_round_data) == 1


def test_deterministic_given_seed(backend):
    s1 = make_gen(FakeBackend()).generate_statement(ISSUE, OPINIONS)
    s2 = make_gen(FakeBackend()).generate_statement(ISSUE, OPINIONS)
    assert s1 == s2


def test_seed_changes_outcome_possible(backend):
    results = {
        make_gen(FakeBackend(), seed=s).generate_statement(ISSUE, OPINIONS)
        for s in (1, 2, 3, 4, 5)
    }
    assert len(results) > 1  # different seeds explore different candidates


def test_rankings_are_valid_permutation_arrays(backend):
    gen = make_gen(backend, num_rounds=0)
    gen.generate_statement(ISSUE, OPINIONS)
    for name, ranking in gen.agent_rankings.items():
        assert ranking is not None, name
        assert sorted(ranking.tolist()) == [0, 1, 2]


def test_zero_rounds_returns_initial_winner(backend):
    gen = make_gen(backend, num_rounds=0)
    statement = gen.generate_statement(ISSUE, OPINIONS)
    assert statement in gen.candidate_statements
    assert gen.all_round_data == []


def test_multi_round_runs_all_rounds(backend):
    gen = make_gen(backend, num_rounds=2)
    statement = gen.generate_statement(ISSUE, OPINIONS)
    assert statement
    assert len(gen.all_round_data) == 2
    for round_data in gen.all_round_data:
        assert "agent_critiques" in round_data
        assert len(round_data["revised_statements"]) == 3  # min(3, 4)


def test_winner_is_schulze_choice(backend):
    gen = make_gen(backend, num_rounds=0)
    statement = gen.generate_statement(ISSUE, OPINIONS)
    from consensus_tpu.social_choice.schulze import aggregate_schulze

    social = aggregate_schulze(
        gen.agent_rankings,
        num_candidates=len(gen.candidate_statements),
        seed=gen._phase_seed("ranking", 0, 99),
        tie_breaking_method="random",
    )
    assert statement == gen.candidate_statements[int(np.argmin(social))]


def test_non_instruction_following_backend_fails_gracefully():
    """A backend that never emits the envelope -> error sentinel, no crash."""
    backend = FakeBackend(instruction_following=False)
    gen = make_gen(backend)
    statement = gen.generate_statement(ISSUE, OPINIONS)
    assert statement.startswith("[ERROR")


def test_ranking_failure_falls_back_to_first_candidate(monkeypatch, backend):
    gen = make_gen(backend)
    monkeypatch.setattr(
        gen, "_rank_all", lambda *a, **k: {name: None for name in OPINIONS}
    )
    statement = gen.generate_statement(ISSUE, OPINIONS)
    assert statement == gen.candidate_statements[0]


def test_timing_fallbacks_run_full_pipeline():
    """pin_budget timing mode: unparseable responses fall back (raw text as
    candidate/critique, identity ranking) so every deliberation phase runs —
    without it a random-weight model short-circuits after the candidate
    phase and a timed cell measures 1 of 4+ phases."""
    from consensus_tpu.backends.tpu import TPUBackend
    from consensus_tpu.methods import get_method_generator

    backend = TPUBackend(model="tiny-gemma2", max_context=256, base_seed=1)
    generator = get_method_generator(
        "habermas_machine",
        backend,
        {"num_candidates": 2, "num_rounds": 1, "max_tokens": 16,
         "seed": 3, "pin_budget": True},
        "tiny-gemma2",
    )
    statement = generator.generate_statement(
        "Trees?", {"Agent 1": "yes", "Agent 2": "no"}
    )
    # Full pipeline ran: candidates exist, every agent ranked (fallback
    # identity at worst), and at least one critique/revision round recorded.
    assert statement and not statement.startswith("[ERROR")
    assert generator.candidate_statements
    assert all(r is not None for r in generator.agent_rankings.values())
    assert generator.all_round_data
    assert generator.all_round_data[0].get("revised_statements")


class _CountingWrapper:
    """Delegating backend wrapper that records temperature-0 generate rows."""

    def __init__(self, inner, deterministic):
        self._inner = inner
        self.deterministic_greedy = deterministic
        self.greedy_rows = 0

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def generate(self, requests):
        self.greedy_rows += sum(1 for r in requests if r.temperature == 0.0)
        return self._inner.generate(requests)


@pytest.mark.parametrize("deterministic,expected_attempts", [(True, 1), (False, 3)])
def test_greedy_ranking_retries_elided_on_deterministic_backends(
    monkeypatch, deterministic, expected_attempts
):
    """Rankings decode at temperature 0; on a backend whose greedy path is
    argmax (seed never enters the program) a seed-incremented retry replays
    the identical response, so habermas elides it.  Nondeterministic
    backends keep the reference's full retry choreography."""
    import consensus_tpu.methods.habermas as habermas_mod

    monkeypatch.setattr(
        habermas_mod, "process_ranking_response", lambda *a, **k: (None, None)
    )
    backend = _CountingWrapper(FakeBackend(), deterministic)
    gen = make_gen(backend, num_retries_on_error=2)
    gen.generate_statement(ISSUE, OPINIONS)
    # All rankings fail to parse -> winner is None -> only the round-0
    # ranking phase runs: one temp-0 request per agent per attempt.
    assert backend.greedy_rows == expected_attempts * len(OPINIONS)
