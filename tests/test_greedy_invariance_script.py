"""End-to-end run of scripts/greedy_batch_invariance_check.py in fake mode.

The hardware check (--quick / full TPU) can't run in CI, but its harness —
composition sweep, target-row extraction, report writing, the
token_identical verdict — can, against the deterministic fake backend.
A harness bug (wrong target row, stale baseline key, broken report path)
fails here before it burns a TPU run.
"""

import json
import os
import pathlib
import subprocess
import sys


def test_fake_backend_mode_end_to_end(tmp_path):
    repo = pathlib.Path(__file__).resolve().parent.parent
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=str(repo),
    )
    proc = subprocess.run(
        [
            sys.executable,
            "scripts/greedy_batch_invariance_check.py",
            "--backend", "fake",
            "--report-dir", str(tmp_path),
        ],
        capture_output=True,
        text=True,
        env=env,
        cwd=str(repo),
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    # The fake backend's greedy decode hashes only (prompt, step), so its
    # output is composition-invariant by construction — the harness must
    # report exactly that.
    assert "token_identical=True" in proc.stdout

    payload = json.loads((tmp_path / "greedy_batch_invariance.json").read_text())
    assert payload["backend"] == "fake"
    assert payload["token_identical"] is True
    assert payload["mismatching_compositions"] == []
    assert len(payload["compositions"]) == 6

    report = (tmp_path / "greedy_batch_invariance.md").read_text()
    assert "INVARIANT" in report
