"""Anytime-decoder tests: BudgetClock semantics, per-method mid-run expiry,
budget scaling, and full-budget bit-identity (the seam must be inert when
unbounded — pinned here, relied on by tests/test_serve.py's acceptance
test and tests/golden/).
"""

import time

import pytest

from consensus_tpu.backends.fake import FakeBackend
from consensus_tpu.experiment import Experiment
from consensus_tpu.methods import get_method_generator
from consensus_tpu.methods.anytime import (
    BudgetClock,
    BudgetExpired,
    observe_welfare_gap,
    record_early_exit,
)
from consensus_tpu.obs.metrics import Registry

ISSUE = "Should the city invest in more bike lanes?"
OPINIONS = {
    "Agent 1": "Bike lanes make streets safer and should be expanded.",
    "Agent 2": "Road space is scarce; cars and buses need priority.",
    "Agent 3": "Invest only where cycling demand is proven.",
}

#: (method, small-but-multi-wave config) — every search method with a seam.
METHOD_CONFIGS = [
    ("best_of_n", {"n": 3, "max_tokens": 16}),
    ("beam_search", {"beam_width": 2, "max_tokens": 6}),
    ("finite_lookahead",
     {"branching_factor": 2, "max_depth": 2, "max_tokens": 6}),
    ("mcts", {"num_simulations": 4, "expansion_sample_width": 2,
              "max_tokens": 4, "rollout_depth": 2}),
    ("habermas_machine", {"num_candidates": 2, "num_rounds": 1,
                          "max_tokens": 40}),
]


@pytest.fixture()
def backend():
    return FakeBackend()


class TestBudgetClock:
    def test_unbounded_never_expires(self):
        clock = BudgetClock.unbounded()
        assert not clock.bounded
        assert not clock.expired()
        assert clock.reason is None
        assert clock.remaining() is None

    def test_deadline_expiry_and_stickiness(self):
        clock = BudgetClock(deadline=time.monotonic() - 0.01)
        assert clock.expired()
        assert clock.reason == "deadline"
        # Sticky: pushing the deadline out does not un-expire it.
        clock.deadline = time.monotonic() + 60.0
        assert clock.expired()

    def test_cancellation_probe_and_stickiness(self):
        flag = {"cancelled": True}
        clock = BudgetClock(cancelled=lambda: flag["cancelled"])
        assert clock.bounded
        assert clock.expired()
        assert clock.reason == "cancelled"
        flag["cancelled"] = False  # latch must hold
        assert clock.expired()

    def test_cancelled_takes_precedence_over_deadline(self):
        clock = BudgetClock(
            deadline=time.monotonic() - 1.0, cancelled=lambda: True
        )
        assert clock.expired()
        assert clock.reason == "cancelled"

    def test_scale_validation(self):
        with pytest.raises(ValueError):
            BudgetClock(scale=0.0)
        with pytest.raises(ValueError):
            BudgetClock(scale=1.5)

    def test_scale_int(self):
        half = BudgetClock(scale=0.5)
        assert half.scale_int(4) == 2
        assert half.scale_int(5) == 3  # ceil
        assert half.scale_int(1) == 1  # floor at 1
        assert half.scale_int(0) == 0  # zero budget preserved
        tiny = BudgetClock(scale=0.01)
        assert tiny.scale_int(10) == 1  # never degenerates to 0
        full = BudgetClock.unbounded()
        assert full.scale_int(7) == 7  # identity

    def test_from_config(self):
        assert not BudgetClock.from_config({}).bounded
        clock = BudgetClock.from_config({"budget_s": 60.0,
                                         "budget_scale": 0.5})
        assert clock.scale == 0.5
        remaining = clock.remaining()
        assert remaining is not None and 0 < remaining <= 60.0


class TestObsHelpers:
    def test_record_early_exit_counts(self):
        registry = Registry()
        record_early_exit("mcts", "deadline", registry=registry)
        record_early_exit("mcts", "deadline", registry=registry)
        snapshot = registry.snapshot()["families"]
        series = snapshot["anytime_early_exits_total"]["series"]
        assert series[0]["value"] == 2

    def test_welfare_gap_clamped_and_recorded(self):
        registry = Registry()
        assert observe_welfare_gap(
            "best_of_n", -1.0, -3.5, registry=registry) == 2.5
        # A degraded run cannot "beat" its own full-budget search.
        assert observe_welfare_gap(
            "best_of_n", -1.0, -0.5, registry=registry) == 0.0
        assert "degraded_welfare_gap" in registry.snapshot()["families"]


class TestFullBudgetIdentity:
    """The seam must be inert without a bound: injecting an explicit
    unbounded clock changes nothing, and nothing is tagged degraded."""

    @pytest.mark.parametrize("method,config", METHOD_CONFIGS)
    def test_unbounded_clock_is_bit_identical(self, method, config):
        plain = get_method_generator(
            method, FakeBackend(), {**config, "seed": 7})
        baseline = plain.generate_statement(ISSUE, OPINIONS)
        assert not plain.degraded

        clocked = get_method_generator(
            method, FakeBackend(), {**config, "seed": 7})
        clocked.budget_clock = BudgetClock.unbounded()
        assert clocked.generate_statement(ISSUE, OPINIONS) == baseline
        assert not clocked.degraded
        assert clocked.budget_spent == {}


def _trip_after_calls(backend, extra_calls):
    """Cancellation probe that fires once ``extra_calls`` more backend
    calls have completed — deterministic mid-run expiry without clocks."""
    start = sum(backend.call_counts.values())

    def probe():
        return sum(backend.call_counts.values()) - start >= extra_calls

    return probe


class TestMidRunExpiry:
    @pytest.mark.parametrize("method,config", METHOD_CONFIGS)
    def test_degrades_to_checkpoint(self, backend, method, config):
        generator = get_method_generator(
            method, backend, {**config, "seed": 7})
        generator.budget_clock = BudgetClock(
            cancelled=_trip_after_calls(backend, 1))
        statement = generator.generate_statement(ISSUE, OPINIONS)
        assert statement  # a real partial, not an error sentinel
        assert generator.degraded
        assert generator.degraded_reason == "cancelled"
        assert generator.budget_spent  # method-specific accounting present
        assert generator.anytime is not None
        assert generator.anytime.checkpoint

    def test_best_of_n_expiry_skips_scoring(self, backend):
        generator = get_method_generator(
            "best_of_n", backend, {"n": 3, "max_tokens": 16, "seed": 7})
        generator.budget_clock = BudgetClock(
            cancelled=_trip_after_calls(backend, 1))
        generator.generate_statement(ISSUE, OPINIONS)
        assert generator.budget_spent["candidates_scored"] == 0
        assert backend.call_counts["score"] == 0

    def test_born_expired_raises_budget_expired(self, backend):
        generator = get_method_generator(
            "best_of_n", backend, {"n": 3, "max_tokens": 16, "seed": 7})
        generator.budget_clock = BudgetClock(
            deadline=time.monotonic() - 0.01)
        with pytest.raises(BudgetExpired) as excinfo:
            generator.generate_statement(ISSUE, OPINIONS)
        assert excinfo.value.method == "best_of_n"
        assert excinfo.value.reason == "deadline"
        assert backend.call_counts["generate"] == 0  # no device time wasted

    def test_early_exit_counter_incremented(self, backend, monkeypatch):
        registry = Registry()
        import consensus_tpu.methods.anytime as anytime_mod
        monkeypatch.setattr(anytime_mod, "get_registry", lambda: registry)
        generator = get_method_generator(
            "best_of_n", backend, {"n": 3, "max_tokens": 16, "seed": 7})
        generator.budget_clock = BudgetClock(
            cancelled=_trip_after_calls(backend, 1))
        generator.generate_statement(ISSUE, OPINIONS)
        family = registry.snapshot()["families"]["anytime_early_exits_total"]
        (series,) = family["series"]
        assert series["labels"] == {"method": "best_of_n",
                                    "reason": "cancelled"}
        assert series["value"] == 1


class TestBudgetScaling:
    def test_best_of_n_scaled_equals_explicit_smaller_n(self):
        """scale=0.5 over n=4 must sample the SAME prefix of candidates as
        an explicit n=2 run (seeds are seed+i), so statements match."""
        scaled = get_method_generator(
            "best_of_n", FakeBackend(),
            {"n": 4, "max_tokens": 16, "seed": 7, "budget_scale": 0.5})
        scaled_statement = scaled.generate_statement(ISSUE, OPINIONS)
        assert scaled.degraded
        assert scaled.degraded_reason == "budget_scaled"
        assert scaled.budget_spent["n_used"] == 2
        assert scaled.budget_spent["n_planned"] == 4
        assert scaled.budget_spent["budget_scale"] == 0.5

        explicit = get_method_generator(
            "best_of_n", FakeBackend(), {"n": 2, "max_tokens": 16, "seed": 7})
        assert scaled_statement == explicit.generate_statement(ISSUE, OPINIONS)
        assert not explicit.degraded

    def test_mcts_scaled_runs_fewer_sims(self):
        scaled = get_method_generator(
            "mcts", FakeBackend(),
            {"num_simulations": 4, "expansion_sample_width": 2,
             "max_tokens": 3, "rollout_depth": 2, "seed": 7,
             "budget_scale": 0.5})
        statement = scaled.generate_statement(ISSUE, OPINIONS)
        assert statement
        assert scaled.degraded
        assert scaled.degraded_reason == "budget_scaled"
        assert scaled.budget_spent["num_simulations"] == 2
        assert scaled.budget_spent["num_simulations_planned"] == 4


class TestExperimentDegradedRows:
    def test_degraded_columns_only_on_degraded_rows(self, tmp_path):
        """budget_scale in a method section produces degraded-tagged rows;
        a plain sweep's CSV schema stays exactly historical (no new
        columns) — the tests/golden/ safety property."""
        config = {
            "experiment_name": "anytime_rows",
            "seed": 42,
            "num_seeds": 1,
            "backend": "fake",
            "scenario": {"issue": ISSUE, "agent_opinions": dict(OPINIONS)},
            "methods_to_run": ["best_of_n"],
            "best_of_n": {"n": 4, "max_tokens": 16, "budget_scale": 0.5},
            "output_dir": str(tmp_path / "scaled"),
        }
        frame = Experiment(config).run()
        assert bool(frame.iloc[0]["degraded"])
        assert frame.iloc[0]["degraded_reason"] == "budget_scaled"
        assert "n_used" in frame.iloc[0]["budget_spent"]

        plain = dict(config)
        plain["best_of_n"] = {"n": 2, "max_tokens": 16}
        plain["output_dir"] = str(tmp_path / "plain")
        plain_frame = Experiment(plain).run()
        for column in ("degraded", "degraded_reason", "budget_spent"):
            assert column not in plain_frame.columns
