"""Shared-trunk generation: decode B rows from ONE prefilled prompt.

best_of_n's N drafts and every habermas phase decode many rows from an
identical prompt (reference best_of_n.py:101-142, habermas_machine.py:
530-583).  The shared path prefills the prompt once and broadcast-attends
it per step (forward_trunk_tail with n_slots=B, n_roles=1) — per-step KV
reads drop from B·(ctx+t) to ctx+B·t.  It must be a pure optimization:
same tokens as the classic per-row-trunk path for the same seeds.
"""

import numpy as np
import pytest

from consensus_tpu.backends.base import GenerationRequest
from consensus_tpu.backends.tpu import TPUBackend


def make_backend(**kw):
    kw.setdefault("model", "tiny-gemma2")
    kw.setdefault("max_context", 128)
    kw.setdefault("base_seed", 0)
    kw.setdefault("dtype", "float32")
    return TPUBackend(**kw)


@pytest.fixture(scope="module")
def shared():
    return make_backend(shared_trunk_generation=True)


@pytest.fixture(scope="module")
def classic():
    return make_backend(shared_trunk_generation=False)


def requests_same_prompt(n, max_tokens=10, temperature=0.0):
    return [
        GenerationRequest(
            user_prompt="One common draft prompt.",
            max_tokens=max_tokens,
            seed=50 + i,
            temperature=temperature,
        )
        for i in range(n)
    ]


def test_shared_matches_classic_greedy(shared, classic):
    """Greedy rows are logit-determined: the shared trunk must reproduce the
    classic path's tokens exactly (identical math, different layout)."""
    requests = requests_same_prompt(6, temperature=0.0)
    ours = shared.generate(requests)
    ref = classic.generate(requests)
    assert [r.token_ids for r in ours] == [r.token_ids for r in ref]


def test_shared_matches_classic_sampled(shared, classic):
    """Sampled rows use the same per-request key streams in both paths."""
    requests = requests_same_prompt(8, temperature=0.9)
    ours = shared.generate(requests)
    ref = classic.generate(requests)
    assert [r.token_ids for r in ours] == [r.token_ids for r in ref]


def test_rows_are_distinct_despite_shared_trunk(shared):
    requests = requests_same_prompt(8, temperature=1.0)
    results = shared.generate(requests)
    assert len({r.token_ids for r in results}) > 1


def test_mixed_batch_routes_both_paths(shared, classic):
    """4 identical prompts ride the shared path, 2 odd ones the classic
    path; result order must be preserved."""
    requests = requests_same_prompt(4, temperature=0.8) + [
        GenerationRequest(
            user_prompt=f"different {i}", max_tokens=8, seed=i, temperature=0.8
        )
        for i in range(2)
    ]
    ours = shared.generate(requests)
    ref = classic.generate(requests)
    assert [r.token_ids for r in ours] == [r.token_ids for r in ref]


def test_shared_respects_stop_and_eos_semantics(shared):
    requests = [
        GenerationRequest(
            user_prompt="One common draft prompt.",
            max_tokens=10,
            seed=i,
            temperature=0.7,
            stop=("e",),
        )
        for i in range(4)
    ]
    for result in shared.generate(requests):
        assert "e" not in result.text
        assert result.finish_reason == "stop" or len(result.token_ids) <= 10


def test_shared_trunk_with_bias_tables(shared, classic):
    requests = [
        GenerationRequest(
            user_prompt="One common draft prompt.",
            max_tokens=8,
            seed=i,
            temperature=0.9,
            bias_against_tokens=("e", "t"),
            bias_value=-100.0,
        )
        for i in range(5)
    ]
    ours = shared.generate(requests)
    ref = classic.generate(requests)
    assert [r.token_ids for r in ours] == [r.token_ids for r in ref]
