"""Shared-trunk generation: decode B rows from ONE prefilled prompt.

best_of_n's N drafts and every habermas phase decode many rows from an
identical prompt (reference best_of_n.py:101-142, habermas_machine.py:
530-583).  The shared path prefills the prompt once and broadcast-attends
it per step (forward_trunk_tail with n_slots=B, n_roles=1) — per-step KV
reads drop from B·(ctx+t) to ctx+B·t.  It must be a pure optimization:
same tokens as the classic per-row-trunk path for the same seeds.
"""

import numpy as np
import pytest

from consensus_tpu.backends.base import GenerationRequest
from consensus_tpu.backends.tpu import TPUBackend


def make_backend(**kw):
    kw.setdefault("model", "tiny-gemma2")
    kw.setdefault("max_context", 128)
    kw.setdefault("base_seed", 0)
    kw.setdefault("dtype", "float32")
    return TPUBackend(**kw)


@pytest.fixture(scope="module")
def shared():
    return make_backend(shared_trunk_generation=True)


@pytest.fixture(scope="module")
def classic():
    return make_backend(shared_trunk_generation=False)


def requests_same_prompt(n, max_tokens=10, temperature=0.0):
    return [
        GenerationRequest(
            user_prompt="One common draft prompt.",
            max_tokens=max_tokens,
            seed=50 + i,
            temperature=temperature,
        )
        for i in range(n)
    ]


def test_shared_matches_classic_greedy(shared, classic):
    """Greedy rows are logit-determined: the shared trunk must reproduce the
    classic path's tokens exactly (identical math, different layout)."""
    requests = requests_same_prompt(6, temperature=0.0)
    ours = shared.generate(requests)
    ref = classic.generate(requests)
    assert [r.token_ids for r in ours] == [r.token_ids for r in ref]


def test_shared_matches_classic_sampled(shared, classic):
    """Sampled rows use the same per-request key streams in both paths."""
    requests = requests_same_prompt(8, temperature=0.9)
    ours = shared.generate(requests)
    ref = classic.generate(requests)
    assert [r.token_ids for r in ours] == [r.token_ids for r in ref]


def test_rows_are_distinct_despite_shared_trunk(shared):
    requests = requests_same_prompt(8, temperature=1.0)
    results = shared.generate(requests)
    assert len({r.token_ids for r in results}) > 1


def test_mixed_batch_routes_both_paths(shared, classic):
    """4 identical prompts ride the shared path, 2 odd ones the classic
    path; result order must be preserved."""
    requests = requests_same_prompt(4, temperature=0.8) + [
        GenerationRequest(
            user_prompt=f"different {i}", max_tokens=8, seed=i, temperature=0.8
        )
        for i in range(2)
    ]
    ours = shared.generate(requests)
    ref = classic.generate(requests)
    assert [r.token_ids for r in ours] == [r.token_ids for r in ref]


def test_shared_respects_stop_and_eos_semantics(shared):
    requests = [
        GenerationRequest(
            user_prompt="One common draft prompt.",
            max_tokens=10,
            seed=i,
            temperature=0.7,
            stop=("e",),
        )
        for i in range(4)
    ]
    for result in shared.generate(requests):
        assert "e" not in result.text
        assert result.finish_reason == "stop" or len(result.token_ids) <= 10


def test_shared_trunk_with_bias_tables(shared, classic):
    requests = [
        GenerationRequest(
            user_prompt="One common draft prompt.",
            max_tokens=8,
            seed=i,
            temperature=0.9,
            bias_against_tokens=("e", "t"),
            bias_value=-100.0,
        )
        for i in range(5)
    ]
    ours = shared.generate(requests)
    ref = classic.generate(requests)
    assert [r.token_ids for r in ours] == [r.token_ids for r in ref]


class TestRoutingThreshold:
    """Small identical-prompt groups inside a larger batch route CLASSIC
    (combined chunks amortize the per-step weight read); big groups and
    whole-batch groups still take the shared path (round-4 routing fix —
    the habermas revision phase is 30 distinct 4-row groups)."""

    def _routes(self, backend, requests, monkeypatch):
        import consensus_tpu.backends.tpu as tpu_mod

        calls = {"shared": 0, "classic": 0}
        orig_shared = tpu_mod.TPUBackend._generate_shared
        orig_classic = tpu_mod.TPUBackend._generate_classic

        def spy_shared(self, reqs, ids):
            calls["shared"] += 1
            return orig_shared(self, reqs, ids)

        def spy_classic(self, reqs, ids):
            calls["classic"] += 1
            return orig_classic(self, reqs, ids)

        monkeypatch.setattr(tpu_mod.TPUBackend, "_generate_shared", spy_shared)
        monkeypatch.setattr(tpu_mod.TPUBackend, "_generate_classic", spy_classic)
        results = backend.generate(requests)
        assert all(r.ok for r in results)
        return calls

    def test_small_groups_in_big_batch_go_classic(self, shared, monkeypatch):
        requests = [
            GenerationRequest(
                user_prompt=f"Revision prompt {g}", max_tokens=8, seed=g * 10 + i
            )
            for g in range(5)
            for i in range(4)  # 5 distinct 4-row groups
        ]
        calls = self._routes(shared, requests, monkeypatch)
        assert calls["shared"] == 0 and calls["classic"] >= 1

    def test_whole_batch_group_stays_shared(self, shared, monkeypatch):
        requests = [
            GenerationRequest(user_prompt="One prompt", max_tokens=8, seed=i)
            for i in range(4)
        ]
        calls = self._routes(shared, requests, monkeypatch)
        assert calls["shared"] == 1 and calls["classic"] == 0

    def test_large_group_in_mixed_batch_stays_shared(self, shared, monkeypatch):
        from consensus_tpu.backends.tpu import _SHARED_TRUNK_SOLO_ROWS

        requests = [
            GenerationRequest(user_prompt="Big group", max_tokens=8, seed=i)
            for i in range(_SHARED_TRUNK_SOLO_ROWS)
        ] + [
            GenerationRequest(user_prompt=f"Stray {i}", max_tokens=8, seed=99 + i)
            for i in range(2)
        ]
        calls = self._routes(shared, requests, monkeypatch)
        assert calls["shared"] == 1 and calls["classic"] >= 1
