"""End-to-end sweep driver (cli/run_sweep) over a tiny fake-backend tree.

The reference's sweep driver shells out one subprocess per config
(run_aamas_experiments.py:66-75); ours runs in-process so compiled programs
are reused — this test pins the glob/filter logic and the full
per-config pipeline wiring without hardware.
"""

import pathlib

import pandas as pd
import yaml

from consensus_tpu.cli.run_sweep import find_config_files, main


def write_tree(root: pathlib.Path):
    scenario = {
        "issue": "Should the park stay open late?",
        "agent_opinions": {
            "Agent 1": "Yes, evenings are the only free time.",
            "Agent 2": "Noise late at night worries me.",
        },
    }
    for model in ("gemma", "llama"):
        for s in (1, 2):
            for method, section in (
                ("quick_bon", {"best_of_n": {"n": 2, "max_tokens": 8, "seed": 1}}),
                ("quick_zero", {"zero_shot": {"max_tokens": 8, "seed": 1}}),
            ):
                cfg = {
                    "experiment_name": f"sweeptest_{model}_s{s}_{method}",
                    "seed": 7,
                    "num_seeds": 1,
                    "backend": "fake",
                    "models": {
                        "generation_model": "fake",
                        "evaluation_models": ["fake"],
                    },
                    "scenario": scenario,
                    "methods_to_run": list(section),
                    "output_dir": str(root / "out"),
                    **section,
                }
                path = root / model / f"scenario_{s}" / f"{method}.yaml"
                path.parent.mkdir(parents=True, exist_ok=True)
                path.write_text(yaml.safe_dump(cfg))


def test_find_config_files_filters(tmp_path):
    write_tree(tmp_path)
    all_configs = find_config_files(str(tmp_path))
    assert len(all_configs) == 8
    gemma_only = find_config_files(str(tmp_path), models=["gemma"])
    assert len(gemma_only) == 4
    s2_bon = find_config_files(
        str(tmp_path), scenarios=[2], methods=["quick_bon"]
    )
    assert len(s2_bon) == 2
    assert all("scenario_2" in str(p) and p.stem == "quick_bon" for p in s2_bon)


def test_sweep_runs_every_matching_config(tmp_path, monkeypatch):
    write_tree(tmp_path)
    monkeypatch.chdir(tmp_path)  # run dirs land under tmp
    rc = main(
        [
            "--configs-root", str(tmp_path),
            "--model", "gemma",
            "--method", "quick_bon",
            "--skip-comparative-ranking",
            "--quiet",
        ]
    )
    assert rc == 0
    results = sorted((tmp_path / "out").glob("*/results.csv"))
    assert len(results) == 2  # gemma x scenario_{1,2} x quick_bon
    for csv in results:
        df = pd.read_csv(csv)
        assert len(df) == 1 and df["error_message"].isna().all()
        agg = csv.parent / "evaluation" / "improved_aggregate" / "aggregated_metrics.csv"
        assert agg.exists()


def test_sweep_timing_pin_budget_reaches_runs(tmp_path, monkeypatch):
    """--timing-pin-budget injects timing_pin_budget into every config: the
    run dir's token_counts.json records pinned_budget=true and the method
    run configs carry pin_budget."""
    import json

    write_tree(tmp_path)
    monkeypatch.chdir(tmp_path)
    rc = main(
        [
            "--configs-root", str(tmp_path),
            "--model", "llama",
            "--scenario", "1",
            "--method", "quick_bon",
            "--skip-comparative-ranking",
            "--timing-pin-budget",
            "--quiet",
        ]
    )
    assert rc == 0
    tokens = sorted((tmp_path / "out").glob("*/token_counts.json"))
    assert tokens, "run dir missing token_counts.json"
    payload = json.loads(tokens[-1].read_text())
    assert payload["pinned_budget"] is True
