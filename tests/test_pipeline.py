"""Experiment engine → evaluation → aggregation pipeline tests (fake backend).

Covers the L4-L7 layers (SURVEY §2.9-2.12) the reference exercises only via
live-API smoke configs.
"""

import numpy as np
import pandas as pd
import pytest
import yaml

from consensus_tpu.aggregation import aggregate_run_dir
from consensus_tpu.backends.fake import FakeBackend
from consensus_tpu.evaluation import StatementEvaluator
from consensus_tpu.experiment import Experiment

ISSUE = "Should the library extend its opening hours?"
OPINIONS = {
    "Agent 1": "Students need late-night study space.",
    "Agent 2": "Staff costs must stay within the current budget.",
    "Agent 3": "Open later on weekends only.",
}


def base_config(tmp_path, **overrides):
    config = {
        "experiment_name": "test_run",
        "seed": 42,
        "num_seeds": 2,
        "backend": "fake",
        "models": {"generation_model": "fake-lm", "evaluation_models": ["fake-lm"]},
        "scenario": {"issue": ISSUE, "agent_opinions": dict(OPINIONS)},
        "methods_to_run": ["zero_shot", "best_of_n"],
        "best_of_n": {"n": [2, 3], "max_tokens": 20},
        "output_dir": str(tmp_path),
    }
    config.update(overrides)
    return config


class TestExperiment:
    def test_param_grid_expansion(self):
        configs = Experiment.expand_param_grid(
            {"a": [1, 2], "b": ["x", "y"], "c": 7}
        )
        assert len(configs) == 4
        assert {"a": 1, "b": "x", "c": 7} in configs
        assert all(cfg["c"] == 7 for cfg in configs)

    def test_scalar_config_passthrough(self):
        assert Experiment.expand_param_grid({"a": 1}) == [{"a": 1}]

    def test_run_produces_results_csv(self, tmp_path):
        experiment = Experiment(base_config(tmp_path))
        frame = experiment.run()
        # 2 seeds x (zero_shot + best_of_n x 2 grid points) = 6 rows.
        assert len(frame) == 6
        assert set(frame["seed"]) == {42, 43}
        assert (frame["evaluation_status"] == "pending").all()
        assert (experiment.run_dir / "results.csv").exists()
        assert (experiment.run_dir / "config.yaml").exists()
        snapshot = yaml.safe_load((experiment.run_dir / "config.yaml").read_text())
        assert snapshot["seed"] == 42

    def test_method_error_becomes_row(self, tmp_path):
        config = base_config(tmp_path, methods_to_run=["predefined"])
        config["predefined"] = {}  # missing statement -> sentinel, not crash
        frame = Experiment(config).run()
        assert len(frame) == 2
        assert frame["statement"].str.startswith("[ERROR").all()

    def test_unknown_method_is_error_row_not_crash(self, tmp_path):
        config = base_config(tmp_path, methods_to_run=["no_such_method"])
        frame = Experiment(config).run()
        assert (frame["error_message"].str.contains("Unknown method")).all()


class TestEvaluator:
    @pytest.fixture()
    def evaluator(self):
        backend = FakeBackend()
        return StatementEvaluator(
            backend, evaluation_model="fake-lm", judge_backend=backend
        )

    def test_metric_schema_matches_reference(self, evaluator):
        metrics = evaluator.evaluate_statement("We should extend hours.", ISSUE, OPINIONS)
        for name in OPINIONS:
            assert f"avg_logprob_{name}" in metrics
            assert f"utility_avg_logprob_{name}" in metrics
            assert f"cosine_similarity_{name}" in metrics
            assert f"perplexity_{name}" in metrics
        for col in (
            "egalitarian_welfare_cosine",
            "utilitarian_welfare_cosine",
            "log_nash_welfare_cosine",
            "egalitarian_welfare_avg_prob",
            "utility_egalitarian_welfare_logprob",
            "egalitarian_welfare_perplexity",
            "utilitarian_welfare_perplexity",
            "log_nash_welfare_perplexity",
        ):
            assert col in metrics, col

    def test_perplexity_egalitarian_is_max(self, evaluator):
        metrics = evaluator.evaluate_statement("A test statement here.", ISSUE, OPINIONS)
        ppls = [metrics[f"perplexity_{name}"] for name in OPINIONS]
        assert metrics["egalitarian_welfare_perplexity"] == pytest.approx(max(ppls))
        assert metrics["utilitarian_welfare_perplexity"] == pytest.approx(sum(ppls))

    def test_perplexity_consistent_with_logprob(self, evaluator):
        metrics = evaluator.evaluate_statement("A test statement here.", ISSUE, OPINIONS)
        for name in OPINIONS:
            assert metrics[f"perplexity_{name}"] == pytest.approx(
                np.exp(-metrics[f"avg_logprob_{name}"])
            )

    def test_judge_scores(self, evaluator):
        metrics = evaluator.evaluate_statement(
            "A test statement here.", ISSUE, OPINIONS, include_llm_judge=True
        )
        for name in OPINIONS:
            score = metrics[f"judge_score_{name}"]
            assert score is None or 1 <= score <= 5
        assert "egalitarian_welfare_judge_score" in metrics

    def test_comparative_rankings(self, evaluator):
        statements = {
            "zero_shot": "Extend hours modestly.",
            "best_of_n (n=3)": "Open late on weekends.",
            "habermas_machine": "Pilot extended hours within budget.",
        }
        frame, reasoning, matrix = evaluator.evaluate_comparative_rankings(
            statements, ISSUE, OPINIONS, seed=7
        )
        # method holds the base name, method_with_params the full key.
        assert set(frame["method_with_params"]) == set(statements)
        assert set(frame["method"]) == {"zero_shot", "best_of_n", "habermas_machine"}
        assert frame.set_index("method_with_params").loc[
            "best_of_n (n=3)", "param_n"
        ] == 3
        for name in OPINIONS:
            ranks = frame[f"rank_{name}"].tolist()
            assert sorted(ranks) == [1, 2, 3]  # valid permutation
        assert frame["is_maximin_best"].sum() >= 1
        assert frame["is_utilitarian_best"].sum() >= 1
        assert len(reasoning) == len(OPINIONS)
        assert matrix["methods"] == list(statements)

    def test_resident_judge_backend(self, tmp_path):
        """``judge_backend: resident`` judges with the generation backend
        itself (no second model) AND activates the per-agent judge scores
        in Phase 2b plus the comparative-ranking artifacts."""
        import yaml

        from consensus_tpu.cli.run_experiment_with_eval import run_pipeline

        import pathlib

        config = base_config(tmp_path, judge_backend="resident", num_seeds=1)
        cfg_path = tmp_path / "cfg.yaml"
        cfg_path.write_text(yaml.safe_dump(config))
        run_dir = pathlib.Path(run_pipeline(str(cfg_path)))
        assert (run_dir / "evaluation/llm_judge/seed_0/ranking_results.csv").exists()
        eval_csv = pd.read_csv(
            run_dir / "evaluation/fake-lm/seed_0/evaluation_results.csv"
        )
        judge_cols = [c for c in eval_csv.columns if c.startswith("judge_score_")]
        assert judge_cols, eval_csv.columns.tolist()

    def test_ranking_reconstruction_fallback(self):
        """A judge emitting only the raw ``ranking`` array (no method map)
        still yields full rank columns — the reference's reconstruction
        fallback (src/evaluation.py:769-801): array entries are 1-indexed
        statement numbers in prompt order, array position is the rank."""
        import json

        from consensus_tpu.backends.base import GenerationResult

        class ArrayOnlyJudge:
            name = "array-only"

            def generate(self, requests):
                return [
                    GenerationResult(
                        text=json.dumps(
                            {"reasoning": "because", "ranking": [2, 3, 1]}
                        )
                    )
                    for _ in requests
                ]

        evaluator = StatementEvaluator(
            backend=FakeBackend(), judge_backend=ArrayOnlyJudge()
        )
        statements = {
            "zero_shot": "A.",
            "best_of_n (n=3)": "B.",
            "habermas_machine": "C.",
        }
        frame, _, _ = evaluator.evaluate_comparative_rankings(
            statements, ISSUE, OPINIONS, seed=7
        )
        by_key = frame.set_index("method_with_params")
        # ranking [2, 3, 1]: statement 2 is rank 1, 3 is rank 2, 1 is rank 3.
        for name in OPINIONS:
            assert by_key.loc["best_of_n (n=3)", f"rank_{name}"] == 1
            assert by_key.loc["habermas_machine", f"rank_{name}"] == 2
            assert by_key.loc["zero_shot", f"rank_{name}"] == 3

    @pytest.mark.parametrize(
        "raw, expected",
        [
            ([2, 3, 1], {"m0": 3, "m1": 1, "m2": 2}),
            ([1, 2, 3], {"m0": 1, "m1": 2, "m2": 3}),
            (["2", "3", "1"], {"m0": 3, "m1": 1, "m2": 2}),  # numeric strings
            ([1, 2], None),  # wrong length
            ([1, 1, 2], None),  # duplicate statement
            ([0, 1, 2], None),  # out-of-range (1-indexed)
            ([1, 2, "x"], None),  # non-numeric
            ("123", None),  # not an array
            (None, None),
        ],
    )
    def test_reconstruct_method_ranking(self, raw, expected):
        from consensus_tpu.evaluation import _reconstruct_method_ranking

        assert _reconstruct_method_ranking(raw, ["m0", "m1", "m2"]) == expected

    def test_results_file_layout(self, tmp_path, evaluator):
        experiment = Experiment(base_config(tmp_path))
        experiment.run()
        frames = evaluator.evaluate_results_file(
            str(experiment.run_dir / "results.csv")
        )
        assert set(frames) == {42, 43}
        for seed_index in (0, 1):
            csv = (
                experiment.run_dir
                / "evaluation"
                / "fake-lm"
                / f"seed_{seed_index}"
                / "evaluation_results.csv"
            )
            assert csv.exists()
            frame = pd.read_csv(csv)
            assert len(frame) == 3
            assert "method_with_params" in frame.columns
            # Int params survive the CSV round-trip in identifiers.
            keys = frame["method_with_params"].tolist()
            assert any("(n=2)" in k or "n=2" in k for k in keys if "best_of_n" in k)


class TestAggregation:
    def test_aggregate_run_dir(self, tmp_path):
        config = base_config(tmp_path)
        experiment = Experiment(config)
        experiment.run()
        backend = experiment.backend
        evaluator = StatementEvaluator(backend, evaluation_model="fake-lm")
        evaluator.evaluate_results_file(str(experiment.run_dir / "results.csv"))

        aggregated = aggregate_run_dir(str(experiment.run_dir))
        assert aggregated is not None
        out = experiment.run_dir / "evaluation" / "improved_aggregate"
        assert (out / "aggregated_metrics.csv").exists()
        assert (out / "aggregated_metrics_raw.csv").exists()
        # Mean/std across the two seeds, model-prefixed.
        cols = aggregated.columns
        assert any(c.startswith("fake-lm_") and c.endswith("_mean") for c in cols)
        assert any(c.endswith("_std") for c in cols)
        # 3 method keys: zero_shot, best_of_n n=2, best_of_n n=3.
        assert len(aggregated) == 3


class TestFullPipelineCLI:
    def test_run_pipeline(self, tmp_path):
        from consensus_tpu.cli.run_experiment_with_eval import run_pipeline

        config = base_config(tmp_path)
        config_path = tmp_path / "config.yaml"
        config_path.write_text(yaml.safe_dump(config))
        run_dir = run_pipeline(str(config_path))
        run_path = pytest.importorskip("pathlib").Path(run_dir)
        assert (run_path / "results.csv").exists()
        assert (
            run_path / "evaluation" / "llm_judge" / "seed_0" / "ranking_results.csv"
        ).exists()
        assert (
            run_path / "evaluation" / "improved_aggregate" / "aggregated_metrics.csv"
        ).exists()
        ranking = pd.read_csv(
            run_path / "evaluation" / "llm_judge" / "seed_0" / "ranking_results.csv"
        )
        assert {"min_rank", "max_rank", "avg_rank", "is_maximin_best"} <= set(
            ranking.columns
        )


class TestSweepDriver:
    def test_find_config_files_filters(self, tmp_path):
        from consensus_tpu.cli.run_sweep import find_config_files

        for model in ("gemma", "llama"):
            for scenario in (1, 2):
                d = tmp_path / model / f"scenario_{scenario}"
                d.mkdir(parents=True)
                (d / "best_of_n.yaml").write_text("x: 1")
                (d / "beam_search.yaml").write_text("x: 1")

        all_configs = find_config_files(str(tmp_path))
        assert len(all_configs) == 8
        assert len(find_config_files(str(tmp_path), models=["gemma"])) == 4
        assert len(find_config_files(str(tmp_path), scenarios=[2])) == 4
        assert (
            len(
                find_config_files(
                    str(tmp_path), models=["llama"], methods=["beam_search"]
                )
            )
            == 2
        )


class TestBasicAggregation:
    def test_basic_layout(self, tmp_path):
        from consensus_tpu.aggregation import aggregate_run_dir_basic

        config = base_config(tmp_path)
        experiment = Experiment(config)
        experiment.run()
        evaluator = StatementEvaluator(
            experiment.backend, evaluation_model="fake-lm"
        )
        evaluator.evaluate_results_file(str(experiment.run_dir / "results.csv"))

        combined = aggregate_run_dir_basic(str(experiment.run_dir))
        assert combined is not None
        out = experiment.run_dir / "evaluation" / "aggregate"
        assert (out / "fake-lm" / "aggregated_metrics.csv").exists()
        assert (out / "combined_metrics.csv").exists()
        assert (out / "simplified_metrics.csv").exists()
        simplified = pd.read_csv(out / "simplified_metrics.csv")
        assert "method_with_params" in simplified.columns
        assert any(
            "egalitarian_welfare_perplexity_mean" in c for c in simplified.columns
        )


class TestTracing:
    def test_spans_accumulate_and_write(self, tmp_path):
        from consensus_tpu.utils.tracing import Tracer

        tracer = Tracer()
        with tracer.span("phase/a"):
            pass
        with tracer.span("phase/a"):
            pass
        with tracer.span("phase/b"):
            pass
        summary = tracer.summary()
        assert summary["phase/a"]["count"] == 2
        assert summary["phase/b"]["count"] == 1
        tracer.write(tmp_path / "timing.json")
        import json

        loaded = json.loads((tmp_path / "timing.json").read_text())
        assert set(loaded) == {"phase/a", "phase/b"}

    def test_experiment_writes_timing(self, tmp_path):
        experiment = Experiment(base_config(tmp_path, num_seeds=1))
        experiment.run()
        import json

        timing = json.loads((experiment.run_dir / "timing.json").read_text())
        assert any(k.startswith("generate/") for k in timing)
