"""Quality-parity harness tests (VERDICT r1 #3) + role="user" scoring."""

import numpy as np
import pytest

from consensus_tpu.backends.fake import FakeBackend
from consensus_tpu.cli.parity_report import (
    build_report,
    load_baseline,
    render_markdown,
    score_statements_batched,
)


@pytest.fixture(scope="module")
def backend():
    return FakeBackend()


class TestBaselineBundle:
    def test_bundle_loads_with_expected_shape(self):
        data = load_baseline()
        assert len(data["runs"]) == 20  # 5 scenarios x 4 sweeps (gemma)
        scenarios = {r["scenario"] for r in data["runs"]}
        sweeps = {r["sweep"] for r in data["runs"]}
        assert scenarios == {1, 2, 3, 4, 5}
        assert sweeps == {
            "beam_search", "finite_lookahead", "habermas_only", "habermas_vs_bon",
        }
        run = next(
            r for r in data["runs"]
            if r["scenario"] == 1 and r["sweep"] == "habermas_vs_bon"
        )
        assert len(run["rows"]) == 36  # 12 cells x 3 seeds
        # BASELINE.md pins these exact aggregates.
        bon50 = next(
            a for a in run["aggregate"]
            if a["method"] == "best_of_n" and a["params"].get("n") == 50
        )
        assert bon50["egalitarian_welfare_perplexity_mean"]["gemma2-9b"] == (
            pytest.approx(5.569077, abs=1e-4)
        )

    def test_statements_are_real_text(self):
        data = load_baseline()
        for run in data["runs"][:3]:
            for row in run["rows"][:2]:
                assert len(row["statement"].split()) >= 3


class TestScoring:
    def test_batched_scoring_matches_per_statement(self, backend):
        statements = ["We should balance privacy and research.", "Another view."]
        opinions = {"A": "Privacy first.", "B": "Research matters."}
        batched = score_statements_batched(
            backend, statements, "Issue?", opinions
        )
        singles = [
            score_statements_batched(backend, [s], "Issue?", opinions)[0]
            for s in statements
        ]
        for b, s in zip(batched, singles):
            assert b["egalitarian_welfare_perplexity"] == pytest.approx(
                s["egalitarian_welfare_perplexity"], rel=1e-6
            )
            assert b["egalitarian_welfare_cosine"] == pytest.approx(
                s["egalitarian_welfare_cosine"], rel=1e-6
            )

    def test_report_structure_and_deltas(self, backend):
        report = build_report(
            backend, scenarios=[1], sweeps=["finite_lookahead"], weights="fake"
        )
        assert report["n_cells"] == 3  # depth in {1,2,3}
        for cell in report["cells"]:
            assert cell["baseline_egalitarian_perplexity"] is not None
            assert "perplexity_delta_pct" in cell
            expected = (
                100.0
                * (
                    cell["local_egalitarian_perplexity"]
                    - cell["baseline_egalitarian_perplexity"]
                )
                / cell["baseline_egalitarian_perplexity"]
            )
            assert cell["perplexity_delta_pct"] == pytest.approx(expected, abs=0.01)
        markdown = render_markdown(report)
        assert "finite_lookahead" in markdown
        assert str(report["mean_abs_perplexity_delta_pct"]) in markdown


class TestUserRoleScoring:
    def test_user_turn_prefix_templates(self):
        from consensus_tpu.models.tokenizer import ByteTokenizer

        tok = ByteTokenizer()
        assert tok.user_turn_prefix("SYS") == "[SYS]SYS[/SYS]\n[USER]"
        assert tok.user_turn_prefix() == "[USER]"

    def test_role_user_differs_from_assistant_on_tpu_backend(self):
        from consensus_tpu.backends.base import ScoreRequest
        from consensus_tpu.backends.tpu import TPUBackend

        backend = TPUBackend(model="tiny-gemma2", max_context=128)
        template = "Here is a consensus statement about the issue."
        as_user = backend.score(
            [ScoreRequest(context=template, continuation=" Privacy matters.",
                          chat=True, role="user")]
        )[0]
        as_assistant = backend.score(
            [ScoreRequest(context=template, continuation=" Privacy matters.",
                          chat=True)]
        )[0]
        assert as_user.ok and as_assistant.ok
        # Different conditioning prefixes -> different distributions.
        assert not np.allclose(as_user.logprobs, as_assistant.logprobs)
