"""Welfare reduction tests (reference semantics: evaluation.py:274-394)."""

import numpy as np
import pytest

from consensus_tpu.ops import (
    egalitarian_welfare,
    log_nash_welfare,
    sanitize_utilities,
    utilitarian_welfare,
    welfare,
)

U = np.array([[0.5, 0.2, 0.9], [0.1, 0.8, 0.3]])


def test_egalitarian_is_min():
    np.testing.assert_allclose(egalitarian_welfare(U), [0.2, 0.1])


def test_utilitarian_is_sum():
    np.testing.assert_allclose(utilitarian_welfare(U), [1.6, 1.2], rtol=1e-6)


def test_log_nash_is_sum_of_logs_with_epsilon():
    expected = np.log(U).sum(axis=1)
    np.testing.assert_allclose(log_nash_welfare(U), expected, rtol=1e-5)
    # zero utility clamps at epsilon instead of -inf
    v = log_nash_welfare(np.array([[0.0, 0.5]]))
    assert np.isfinite(v).all()
    np.testing.assert_allclose(v, np.log(1e-9) + np.log(0.5), rtol=1e-5)


def test_welfare_dispatch_and_axis():
    np.testing.assert_allclose(welfare(U, "egalitarian", axis=0), U.min(axis=0))
    with pytest.raises(ValueError):
        welfare(U, "nash_product")


def test_sanitize_matches_best_of_n_policy():
    raw = np.array([np.nan, np.inf, -np.inf, 1.5])
    out = np.asarray(sanitize_utilities(raw))
    np.testing.assert_allclose(out, [-10.0, 20.0, -20.0, 1.5])
