"""Gemma-2-9B int8 on ONE v5e chip: the config that cannot exist in bf16.

The 9B bf16 tree is 18.5 GB — over a v5e's 16 GB HBM — so this model is
single-chip-feasible ONLY via the weight-only int8 path (models/quant.py,
~9.3 GB).  This script proves the claim end-to-end on real hardware:
build a random int8 tree on the host (random weights are noise either
way, so we synthesize int8 directly instead of paying a 9B float init),
ship it to the chip, and drive generate + teacher-forced scoring through
TPUBackend.

Usage: python scripts/feasibility_9b.py   (repo root, free chip)
"""
import time

import numpy as np
import jax
import jax.numpy as jnp

from consensus_tpu.backends.base import GenerationRequest, ScoreRequest
from consensus_tpu.backends.tpu import TPUBackend
from consensus_tpu.models.config import get_model_config
from consensus_tpu.models.quant import QTensor


def random_int8_params(config, seed: int = 0, dtype=jnp.bfloat16):
    """A quantize_params-shaped tree with synthesized int8 leaves.

    Mirrors transformer.init_params' layout (stacked layers) and
    quant.quantize_params' scale conventions: matmul weights carry
    (L, 1, d_out) scales, the (V, D) embedding (tied head) per-row (V, 1)
    scales.  Scales are sized so activations stay O(1) like init_params'
    fan-in scaling.
    """
    c = config
    cpu = jax.local_devices(backend="cpu")[0]
    rng = np.random.default_rng(seed)

    def qleaf(*shape, contract_axis, fan_in):
        q = rng.integers(-127, 128, size=shape, dtype=np.int8)
        scale_shape = list(shape)
        scale_shape[contract_axis] = 1
        # int8 values are ~uniform(-127,127) (std ~73); match init_params'
        # N(0, fan_in^-0.5) weight std.
        scale = np.full(scale_shape, (fan_in**-0.5) / 73.0, np.float32)
        return QTensor(
            q=jax.device_put(q, cpu),
            scale=jax.device_put(scale, cpu),
            compute_dtype=dtype,
        )

    h, kv, hd, L, D, F = (
        c.n_heads, c.n_kv_heads, c.head_dim, c.n_layers, c.d_model, c.ffn_hidden,
    )
    zeros = lambda *s: jax.device_put(np.zeros(s, dtype), cpu)  # noqa: E731
    layers = {
        "attn_norm": zeros(L, D),
        "wq": qleaf(L, D, h * hd, contract_axis=-2, fan_in=D),
        "wk": qleaf(L, D, kv * hd, contract_axis=-2, fan_in=D),
        "wv": qleaf(L, D, kv * hd, contract_axis=-2, fan_in=D),
        "wo": qleaf(L, h * hd, D, contract_axis=-2, fan_in=h * hd),
        "ffn_norm": zeros(L, D),
        "w_gate": qleaf(L, D, F, contract_axis=-2, fan_in=D),
        "w_up": qleaf(L, D, F, contract_axis=-2, fan_in=D),
        "w_down": qleaf(L, F, D, contract_axis=-2, fan_in=F),
    }
    if c.use_post_norms:
        layers["post_attn_norm"] = zeros(L, D)
        layers["post_ffn_norm"] = zeros(L, D)
    params = {
        "embed": qleaf(c.vocab_size, D, contract_axis=-1, fan_in=2500),
        "layers": layers,
        "final_norm": zeros(D),
    }
    if not c.tie_lm_head:
        params["lm_head"] = qleaf(c.vocab_size, D, contract_axis=-1, fan_in=D)
    return params


def main():
    cfg = get_model_config("gemma2-9b")
    t0 = time.time()
    host_tree = random_int8_params(cfg)
    print(f"host int8 synthesis: {time.time()-t0:.1f}s", flush=True)

    t0 = time.time()
    device_tree = jax.device_put(host_tree, jax.devices()[0])
    jax.block_until_ready(jax.tree.leaves(device_tree))
    print(f"host->chip transfer: {time.time()-t0:.1f}s", flush=True)

    backend = TPUBackend(
        model="gemma2-9b",
        dtype="bfloat16",
        max_context=512,
        use_flash_attention=True,
        max_batch_rows=8,
        quantization="int8",
        params=device_tree,
        base_seed=0,
    )
    print(f"on-chip param bytes: {backend._params_bytes/1e9:.2f} GB", flush=True)

    reqs = [
        GenerationRequest(user_prompt=f"Opinion {i}: taxes.", max_tokens=32, seed=i)
        for i in range(4)
    ]
    t0 = time.time()
    out = backend.generate(reqs)
    print(f"generate 4x32 tok (incl. compile): {time.time()-t0:.1f}s; "
          f"finish={[r.finish_reason for r in out]}", flush=True)
    t0 = time.time()
    out = backend.generate(reqs)
    dt = time.time() - t0
    print(f"generate warm: {dt:.2f}s -> {1e3*dt/32:.1f} ms/step at B=4", flush=True)

    sreqs = [
        ScoreRequest(context=f"Issue {i}.", continuation="A fair consensus statement.")
        for i in range(4)
    ]
    t0 = time.time()
    scores = backend.score(sreqs)
    print(f"score 4 rows (incl. compile): {time.time()-t0:.1f}s "
          f"ok={[s.ok for s in scores]}", flush=True)


if __name__ == "__main__":
    main()
