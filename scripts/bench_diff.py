#!/usr/bin/env python
"""Diff two BENCH_rNN.json snapshots key-by-key with regression gates.

The bench snapshots (``BENCH_r01.json`` .. in the repo root) record the
tail JSON of a full ``bench.py`` run: one headline metric plus a flat
``extra`` dict of per-cell numbers.  This script flattens both files to
dotted numeric keys, compares them, and applies per-metric regression
thresholds — direction-aware (throughput regressing means DOWN, latency
regressing means UP), with generous bounds because the committed
snapshots come from 1-trial CPU smoke runs.

Exit status is nonzero when any gated metric regressed beyond its
threshold (or a gated metric present in the old snapshot vanished from
the new one — an env-gated cell silently breaking looks exactly like
that).  Ungated keys are reported informationally and never fail.

Usage:

    python scripts/bench_diff.py BENCH_r08.json BENCH_r09.json
    python scripts/bench_diff.py --latest-pair        # two newest by n
    python scripts/bench_diff.py --latest-pair --max-regression 0.75
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import pathlib
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent

#: Gated metrics: (key glob, direction, max adverse relative change).
#: Direction "higher" = bigger is better (throughput), "lower" = smaller
#: is better (latency).  First match wins.  Bounds are wide on purpose —
#: the snapshots are single-trial CPU smoke runs, and the gate exists to
#: catch order-of-magnitude cell breakage, not 5% jitter.
DEFAULT_GATES: List[Tuple[str, str, float]] = [
    ("value", "higher", 0.5),
    ("extra.tokens_per_sec", "higher", 0.5),
    ("extra.engine_statements_per_sec", "higher", 0.5),
    ("extra.engine_vs_legacy_throughput", "higher", 0.4),
    ("extra.engine_k8_statements_per_sec", "higher", 0.5),
    ("extra.bon_latency_seconds_per_statement", "lower", 1.0),
    ("extra.beam_search_seconds_per_statement", "lower", 1.0),
    ("extra.finite_lookahead_seconds_per_statement", "lower", 1.0),
    ("extra.serve_throughput_rps", "higher", 0.5),
    ("extra.serve_p99_ms", "lower", 1.5),
    ("extra.chaos_success_frac", "higher", 0.15),
    # Transport-seam chaos conformance (PR 19): availability under the
    # standard seeded seam schedule should hold near 1.0 (the request
    # path never crosses the seam); recovery time and tail latency are
    # probe-cadence-scale numbers with wide CPU-smoke bounds.
    ("extra.chaos_fleet_availability", "higher", 0.15),
    ("extra.chaos_fleet_p99_ms", "lower", 1.5),
    ("extra.chaos_recovery_time_s", "lower", 1.5),
    ("extra.brownout_availability", "higher", 0.15),
    ("extra.fleet_availability", "higher", 0.15),
    ("extra.padding_efficiency", "higher", 0.3),
    ("extra.engine_padding_efficiency", "higher", 0.3),
    ("extra.bench_obs.throughput_on_rps", "higher", 0.5),
    ("extra.spec_statements_per_sec", "higher", 0.5),
    ("extra.spec_k1_tokens_per_dispatch", "higher", 0.2),
    ("extra.spec_stream_cells.k1_spec.draft_acceptance_rate",
     "higher", 0.5),
    # Durable-state rolling restart (PR 20): availability through the
    # full drain->respawn->warm-seed->rejoin cycle should hold >= 0.99;
    # warm-seed fraction is 1.0 when every respawn restored runs from the
    # durable PageStore; recovery time is probe-cadence-scale with wide
    # CPU-smoke bounds.
    ("extra.restart_availability", "higher", 0.15),
    ("extra.restart_warm_seed_fraction", "higher", 0.3),
    ("extra.restart_recovery_time_s", "lower", 1.5),
    # Corpus-driven load (PR 18): throughput and cache hits may wobble on
    # a loaded CI box; the welfare gap is a deterministic fake-backend
    # golden, so ANY drift there is a real fairness regression.
    ("extra.corpus_statements_per_sec", "higher", 0.5),
    ("extra.corpus_prefix_hit_fraction", "higher", 0.3),
    ("extra.welfare_gap_polarized", "equal", 0.001),
]


def flatten(value: Any, prefix: str = "") -> Dict[str, float]:
    """Dotted numeric leaves of a nested dict (bools excluded)."""
    out: Dict[str, float] = {}
    if isinstance(value, dict):
        for key, sub in value.items():
            path = f"{prefix}.{key}" if prefix else str(key)
            out.update(flatten(sub, path))
    elif isinstance(value, (int, float)) and not isinstance(value, bool):
        out[prefix] = float(value)
    return out


def load_snapshot(path: pathlib.Path) -> Dict[str, float]:
    """BENCH_rNN.json -> flat metric dict (from the run's tail JSON)."""
    snap = json.loads(path.read_text())
    if snap.get("rc", 0) != 0:
        raise SystemExit(f"{path.name}: bench run recorded rc={snap['rc']}")
    tail = snap.get("tail", "")
    # The tail is the last stdout line(s); the metric record is the last
    # parseable JSON object line.
    record = None
    for line in tail.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            record = json.loads(line)
        except ValueError:
            continue
    if not isinstance(record, dict):
        raise SystemExit(f"{path.name}: no JSON metric record in tail")
    return flatten(record)


def gate_for(key: str, gates: List[Tuple[str, str, float]]):
    for pattern, direction, bound in gates:
        if fnmatch.fnmatch(key, pattern):
            return direction, bound
    return None


def adverse_change(
    old: float, new: float, direction: str
) -> Optional[float]:
    """Relative change in the BAD direction (None when not adverse).

    ``direction`` is ``higher``/``lower`` (which way is better) or
    ``equal`` for pinned values where drift in EITHER direction is a
    regression (deterministic goldens surfaced through bench)."""
    if old == 0:
        return None  # no baseline to regress against
    rel = (new - old) / abs(old)
    if direction == "higher" and rel < 0:
        return -rel
    if direction == "lower" and rel > 0:
        return rel
    if direction == "equal" and rel != 0:
        return abs(rel)
    return None


def latest_pair() -> Tuple[pathlib.Path, pathlib.Path]:
    snaps = sorted(
        REPO_ROOT.glob("BENCH_r*.json"),
        key=lambda p: json.loads(p.read_text()).get("n", 0),
    )
    if len(snaps) < 2:
        raise SystemExit("need at least two BENCH_r*.json snapshots")
    return snaps[-2], snaps[-1]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("old", nargs="?", help="older BENCH_rNN.json")
    parser.add_argument("new", nargs="?", help="newer BENCH_rNN.json")
    parser.add_argument("--latest-pair", action="store_true",
                        help="diff the two newest snapshots in the repo "
                             "root (by their recorded n)")
    parser.add_argument("--max-regression", type=float, default=None,
                        help="override every gate's threshold with one "
                             "adverse relative bound (e.g. 0.75)")
    parser.add_argument("--gates-json", default=None,
                        help="JSON list of [key_glob, direction, bound] "
                             "triples replacing the built-in gate table")
    args = parser.parse_args(argv)

    if args.latest_pair:
        old_path, new_path = latest_pair()
    elif args.old and args.new:
        old_path, new_path = pathlib.Path(args.old), pathlib.Path(args.new)
    else:
        parser.error("give OLD NEW paths or --latest-pair")

    gates = DEFAULT_GATES
    if args.gates_json:
        gates = [tuple(g) for g in json.loads(args.gates_json)]
    if args.max_regression is not None:
        gates = [(p, d, args.max_regression) for p, d, _ in gates]

    old = load_snapshot(old_path)
    new = load_snapshot(new_path)

    regressions: List[str] = []
    rows: List[str] = []
    for key in sorted(set(old) | set(new)):
        gate = gate_for(key, gates)
        o, n = old.get(key), new.get(key)
        if o is None:
            rows.append(f"  NEW       {key} = {n}")
            continue
        if n is None:
            if gate is not None:
                regressions.append(f"{key}: present in {old_path.name} "
                                   f"but missing from {new_path.name}")
                rows.append(f"  MISSING!  {key} (was {o})")
            else:
                rows.append(f"  dropped   {key} (was {o})")
            continue
        if gate is None:
            if o != n:
                rows.append(f"  info      {key}: {o} -> {n}")
            continue
        direction, bound = gate
        adverse = adverse_change(o, n, direction)
        if adverse is not None and adverse > bound:
            expectation = ("pinned value" if direction == "equal"
                           else f"{direction} is better")
            regressions.append(
                f"{key}: {o} -> {n} ({expectation}; adverse "
                f"{adverse:.1%} > {bound:.0%} threshold)"
            )
            rows.append(f"  REGRESS!  {key}: {o} -> {n} (-{adverse:.1%})")
        else:
            delta = "" if o == n else f" ({(n - o) / abs(o):+.1%})" \
                if o else ""
            rows.append(f"  ok        {key}: {o} -> {n}{delta}")

    print(f"bench diff: {old_path.name} -> {new_path.name}")
    for row in rows:
        print(row)
    if regressions:
        print(f"\n{len(regressions)} regression(s):", file=sys.stderr)
        for item in regressions:
            print(f"  {item}", file=sys.stderr)
        return 1
    print("\nno gated regressions")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
