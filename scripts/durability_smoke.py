#!/usr/bin/env python
"""SIGKILL -> relaunch durability conformance harness.

Proves the crash-consistency contract end to end, against REAL process
death (no in-process simulation):

1. Launch ``python -m consensus_tpu.serve --state-dir DIR`` as a
   subprocess and resolve a few requests (recording their statements).
2. Queue a burst of further requests and ``SIGKILL`` the server while
   they are admitted-but-unresolved — the journal is left unsealed.
3. Relaunch with the same ``--state-dir``.  The server must replay the
   unresolved entries through normal admission (``replayed > 0``) and
   drain them to zero (``lost == 0``).
4. Re-ask EVERY request: each must answer 200 with a statement
   byte-identical to the first answer where one exists, and asking twice
   must serve from the idempotency cache both times (``dup == 0`` — no
   request is ever recomputed into a different answer).

Prints one JSON verdict on stdout and exits non-zero on any violation.
Used by the tier-1 CI durability smoke step.
"""

from __future__ import annotations

import json
import os
import pathlib
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _payload(index: int) -> dict:
    return {
        "issue": f"Durability smoke issue {index}: should the city expand "
                 "night bus service?",
        "agent_opinions": {
            "Agent 1": f"Yes, shift workers need route {index}.",
            "Agent 2": "Only if daytime frequency is protected.",
        },
        "method": "best_of_n",
        "params": {"n": 4, "max_tokens": 32},
        "seed": 1000 + index,
        "request_id": f"smoke-{index}",
    }


def _launch(state_dir: str) -> tuple:
    env = dict(os.environ, PYTHONUNBUFFERED="1", JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "consensus_tpu.serve", "--backend", "fake",
         "--port", "0", "--max-inflight", "1", "--state-dir", state_dir],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        cwd=str(REPO), env=env, text=True,
    )
    line = proc.stdout.readline()
    try:
        base_url = json.loads(line)["serving"]
    except Exception:
        proc.kill()
        raise RuntimeError(f"server did not announce itself: {line!r}")
    return proc, base_url


def _post(base_url: str, payload: dict, timeout: float = 30.0) -> dict:
    request = urllib.request.Request(
        base_url.rstrip("/") + "/v1/consensus",
        data=json.dumps(payload).encode("utf-8"),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(request, timeout=timeout) as response:
        return json.loads(response.read().decode("utf-8"))


def _healthz(base_url: str) -> dict:
    with urllib.request.urlopen(
        base_url.rstrip("/") + "/healthz", timeout=5.0
    ) as response:
        return json.loads(response.read().decode("utf-8"))


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--state-dir", default=None,
                        help="durable state dir (default: fresh tempdir)")
    parser.add_argument("--resolved", type=int, default=3,
                        help="requests resolved before the kill")
    parser.add_argument("--inflight", type=int, default=5,
                        help="requests admitted-but-unresolved at the kill")
    args = parser.parse_args(argv)

    state_dir = args.state_dir or tempfile.mkdtemp(prefix="durability_smoke_")
    verdict = {"state_dir": state_dir, "resolved_before_kill": 0,
               "replayed": 0, "lost": None, "dup": 0, "mismatches": 0,
               "ok": False}

    # -- life 1: resolve a few, then SIGKILL with a full queue ------------
    proc, base_url = _launch(state_dir)
    answers = {}
    try:
        for i in range(args.resolved):
            body = _post(base_url, _payload(i))
            answers[i] = body["statement"]
        verdict["resolved_before_kill"] = len(answers)
        # Queue the victim burst: max-inflight is 1, so most of these sit
        # admitted (journaled) but unresolved — poll the journal's own
        # unresolved gauge and kill the instant it shows a backlog, so
        # the SIGKILL deterministically lands mid-load.
        def _fire_and_forget(payload: dict) -> None:
            try:
                _post(base_url, payload, timeout=60.0)
            except Exception:
                pass  # the SIGKILL severs these connections — expected

        burst = [threading.Thread(
            target=_fire_and_forget, args=(_payload(args.resolved + j),),
            daemon=True) for j in range(args.inflight)]
        for thread in burst:
            thread.start()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            wal_stats = (_healthz(base_url).get("durability") or {}).get(
                "wal") or {}
            if wal_stats.get("unresolved", 0) >= max(2, args.inflight - 2):
                break
            time.sleep(0.005)
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10.0)

    # -- life 2: relaunch, replay, verify exactly-once --------------------
    proc, base_url = _launch(state_dir)
    try:
        # Replay happens inside start() (before the announce line), but
        # the replayed requests resolve asynchronously — wait for the
        # journal to drain to zero unresolved.
        deadline = time.monotonic() + 60.0
        wal_stats = {}
        while time.monotonic() < deadline:
            wal_stats = (_healthz(base_url).get("durability") or {}).get(
                "wal") or {}
            if wal_stats.get("unresolved", 1) == 0:
                break
            time.sleep(0.1)
        verdict["replayed"] = wal_stats.get("replayed", 0)
        verdict["lost"] = wal_stats.get("unresolved")
        # Exactly-once at the result layer: every request answers, twice,
        # byte-identically; the second ask must come from the idempotency
        # cache (a recompute that could diverge counts as a duplicate).
        for i in range(args.resolved + args.inflight):
            first = _post(base_url, _payload(i))
            second = _post(base_url, _payload(i))
            if i in answers and first["statement"] != answers[i]:
                verdict["mismatches"] += 1
            if (first["statement"] != second["statement"]
                    or not second.get("idempotent_replay")):
                verdict["dup"] += 1
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=30.0)
        except subprocess.TimeoutExpired:
            proc.kill()

    verdict["ok"] = (verdict["replayed"] > 0 and verdict["lost"] == 0
                     and verdict["dup"] == 0 and verdict["mismatches"] == 0)
    print(json.dumps(verdict, indent=2))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
