"""A/B: pallas decode-attention kernel vs einsum path on a beam session.

Times advance_and_propose steps of a beam-8 session (the reference's
widest beam grid, configs/appendix/*/beam_search.yaml) on the real chip,
einsum vs kernel, interleaved trials, medians (VERDICT r2 #10).

Usage: PYTHONPATH=. python scripts/decode_attention_ab.py [--steps 40]
       [--beam 8] [--quick]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import statistics
import time

from consensus_tpu.backends.session import SearchSpec
from consensus_tpu.backends.tpu import TPUBackend, TPUTokenSearchSession
from consensus_tpu.data.aamas_scenarios import SCENARIOS
from consensus_tpu.methods.prompts import agent_prompt, reference_prompt


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=40)
    parser.add_argument("--beam", type=int, default=8)
    parser.add_argument("--trials", type=int, default=3)
    parser.add_argument("--model", default="gemma2-2b")
    parser.add_argument("--quick", action="store_true")
    args = parser.parse_args()

    model = "tiny-gemma2" if args.quick else args.model
    scenario = SCENARIOS[1]
    issue, opinions = scenario["issue"], scenario["agent_opinions"]
    system, user = reference_prompt(issue, opinions, variant="beam_search")
    agent_prompts = tuple(
        agent_prompt(issue, opinion, variant="beam_search")
        for opinion in opinions.values()
    )

    def run_session(backend, seed):
        spec = SearchSpec(
            ref_system=system,
            ref_user=user,
            agent_prompts=agent_prompts,
            n_slots=args.beam,
            k=args.beam,
            temperature=1.0,
            seed=seed,
            sample=True,
            max_steps=args.steps + 2,
        )
        session = TPUTokenSearchSession(backend, spec)
        try:
            props = session.propose()
            # warm the step program
            props = session.advance_and_propose(
                list(range(args.beam)), [slot[0] for slot in props]
            )
            start = time.perf_counter()
            for _ in range(args.steps):
                props = session.advance_and_propose(
                    list(range(args.beam)), [slot[0] for slot in props]
                )
            elapsed = time.perf_counter() - start
        finally:
            session.close()
        return 1000.0 * elapsed / args.steps  # ms/step

    backends = {}
    for use_kernel in (False, True):
        backend = TPUBackend(
            model=model,
            max_context=1024 if not args.quick else 256,
            base_seed=0,
            quantization=None if args.quick else "int8",
        )
        if use_kernel:
            backend.config = dataclasses.replace(
                backend.config, use_decode_attention=True
            )
        backends[use_kernel] = backend

    print("warmup (compiles both arms)...", flush=True)
    run_session(backends[False], 900)
    run_session(backends[True], 900)

    ms = {False: [], True: []}
    for trial in range(args.trials):
        for use_kernel in (False, True):
            step_ms = run_session(backends[use_kernel], 100 + trial)
            ms[use_kernel].append(step_ms)
            print(
                f"trial {trial} kernel={int(use_kernel)}: {step_ms:.1f} ms/step",
                flush=True,
            )

    med = statistics.median
    print(
        json.dumps(
            {
                "model": model,
                "beam": args.beam,
                "steps": args.steps,
                "ms_per_step_einsum": round(med(ms[False]), 2),
                "ms_per_step_kernel": round(med(ms[True]), 2),
                "speedup": round(med(ms[False]) / max(med(ms[True]), 1e-9), 3),
            }
        )
    )


if __name__ == "__main__":
    main()
