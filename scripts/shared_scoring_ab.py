"""In-situ A/B: shared-context scoring ON vs OFF on the real chip.

Round-2 microbenches showed 3.4x on bon-shaped scoring batches, but the
in-situ cell timings were too noisy to certify (shared tunneled chip).
This script certifies the end-to-end effect the way VERDICT r2 #3 asks:
repeated INTERLEAVED runs of the same real best_of_n statement (so ambient
service variance hits both arms equally), medians reported, scoring phase
timed separately from generation (generation is identical in both arms).

Usage: python scripts/shared_scoring_ab.py [--trials 5] [--n 32] [--quick]
(repo root, free chip — don't run during a timed sweep)
"""

from __future__ import annotations

import argparse
import json
import statistics
import time

from consensus_tpu.backends.tpu import TPUBackend
from consensus_tpu.data.aamas_scenarios import SCENARIOS
from consensus_tpu.methods import get_method_generator


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--trials", type=int, default=5)
    parser.add_argument("--n", type=int, default=32, help="best_of_n candidates")
    parser.add_argument("--max-tokens", type=int, default=50)
    parser.add_argument("--model", default="gemma2-2b")
    parser.add_argument("--quick", action="store_true", help="tiny model, CPU-ok smoke")
    args = parser.parse_args()

    model = "tiny-gemma2" if args.quick else args.model
    backend = TPUBackend(
        model=model,
        max_context=1024,
        base_seed=0,
        use_flash_attention=not args.quick,
        max_batch_rows=32,
        quantization=None if args.quick else "int8",
        shared_context_scoring=True,  # flipped per-arm below
    )

    scenario = SCENARIOS[1]
    issue, opinions = scenario["issue"], scenario["agent_opinions"]

    # Time the scoring phase separately: generation is identical in both
    # arms, so the score-call delta is the certified effect.
    score_time = {"t": 0.0}
    inner_score = backend.score

    def timed_score(requests):
        t0 = time.perf_counter()
        out = inner_score(requests)
        score_time["t"] += time.perf_counter() - t0
        return out

    backend.score = timed_score

    def run_once(shared: bool, seed: int):
        backend.shared_context_scoring = shared
        generator = get_method_generator(
            "best_of_n",
            backend,
            {"n": args.n, "max_tokens": args.max_tokens, "seed": seed},
            model,
        )
        score_time["t"] = 0.0
        t0 = time.perf_counter()
        generator.generate_statement(issue, opinions)
        return time.perf_counter() - t0, score_time["t"]

    print(f"warmup (compiles both arms, {model}, n={args.n}) ...", flush=True)
    run_once(True, seed=9000)
    run_once(False, seed=9000)

    totals = {True: [], False: []}
    scores = {True: [], False: []}
    for trial in range(args.trials):
        for shared in (True, False):
            total, score = run_once(shared, seed=100 + trial)
            totals[shared].append(total)
            scores[shared].append(score)
            print(
                f"trial {trial} shared={int(shared)}: "
                f"total {total:.2f}s score {score:.2f}s",
                flush=True,
            )

    med = statistics.median
    result = {
        "model": model,
        "n_candidates": args.n,
        "n_agents": len(opinions),
        "trials": args.trials,
        "total_s_shared": round(med(totals[True]), 3),
        "total_s_classic": round(med(totals[False]), 3),
        "score_s_shared": round(med(scores[True]), 3),
        "score_s_classic": round(med(scores[False]), 3),
        "score_speedup": round(med(scores[False]) / max(med(scores[True]), 1e-9), 2),
        "total_speedup": round(med(totals[False]) / max(med(totals[True]), 1e-9), 2),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
