"""Microbench: CLASSIC-layout segmented decode step time vs batch rows.

The pinned habermas profile shows ranking/critique phases (per-agent
prompts -> classic layout, per-row 1024-col trunks) decoding 768-token
budgets in 32-row dispatches at ~12.6 ms/step — while per-step cost is
dominated by the weight read, i.e. nearly flat in rows.  If a 64- or
96-row classic decode holds (HBM: per-row int8 trunk 54 MB) the phase
cost per row-token drops accordingly.  This script measures it directly:
prefill + segmented decode at B in {32, 48, 64, 96}, ctx 1024, budget
768, int8 weights + kv_quant (the production config).

Usage: PYTHONPATH=/root/.axon_site:/root/repo python scripts/classic_decode_bench.py
"""

from __future__ import annotations

import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from consensus_tpu.models.config import get_model_config
from consensus_tpu.models.generate import generate_tokens_segmented
from consensus_tpu.models.quant import quantize_params
from consensus_tpu.models.transformer import init_params

CTX = int(os.environ.get("BENCH_CTX", "1024"))
MAX_NEW = int(os.environ.get("BENCH_MAX_NEW", "768"))
SEG_LEN = int(os.environ.get("BENCH_SEG_LEN", "128"))
MODEL = os.environ.get("BENCH_MODEL", "gemma2-2b")
BATCHES = tuple(
    int(b) for b in os.environ.get("BENCH_BATCHES", "32,48,64,96").split(",")
)


def run_arm(params, config, batch):
    rng = np.random.default_rng(0)
    tokens = rng.integers(1, 1000, size=(batch, CTX)).astype(np.int32)
    valid = np.ones((batch, CTX), bool)
    keys = jax.vmap(lambda i: jax.random.fold_in(jax.random.PRNGKey(0), i))(
        jnp.arange(batch)
    )
    args = dict(
        key=keys,
        max_new_tokens=MAX_NEW,
        seg_len=SEG_LEN,
        temperature=jnp.zeros((batch,), jnp.float32),  # greedy: ranking shape
        eos_ids=jnp.asarray([-1], jnp.int32),  # pinned budget: no early exit
        pad_id=0,
        kv_quant=True,
    )
    out = generate_tokens_segmented(
        params, config, jnp.asarray(tokens), jnp.asarray(valid), **args
    )
    np.asarray(out.tokens)  # warm (compile)
    t0 = time.perf_counter()
    out = generate_tokens_segmented(
        params, config, jnp.asarray(tokens), jnp.asarray(valid), **args
    )
    np.asarray(out.tokens)
    wall = time.perf_counter() - t0
    print(
        f"classic-seg int8+kvq B={batch:3d} ctx={CTX} T={MAX_NEW} "
        f"wall={wall:7.2f}s  {1000 * wall / MAX_NEW:6.2f} ms/step  "
        f"{1000 * wall / (MAX_NEW * batch):6.3f} ms/row-token",
        flush=True,
    )


def main():
    config = get_model_config(MODEL)
    print(f"model={MODEL} devices={jax.devices()}", flush=True)
    host = jax.devices("cpu")[0]
    with jax.default_device(host):
        params = init_params(config, jax.random.PRNGKey(0), jnp.bfloat16)
        params = quantize_params(params)
    params = jax.device_put(params)
    for batch in BATCHES:
        try:
            run_arm(params, config, batch)
        except Exception as exc:  # OOM arms report and continue
            print(f"classic-seg B={batch}: FAILED: {type(exc).__name__}: {exc}")


if __name__ == "__main__":
    main()
