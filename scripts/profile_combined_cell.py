"""Profile one north-star habermas_vs_best_of_n cell: FULL pipeline.

Companion to profile_habermas_cell.py, for the sweep's dominant cells
(~700-810 s each, 5 of 20 configs but ~2/3 of the 92-min wall).  Runs the
complete run_pipeline (generation + evaluation + aggregation) with the
backend's generate/score instrumented, and prints a phase/dispatch
breakdown so the ~500 s the cell spends beyond habermas generation is
attributed.
"""

from __future__ import annotations

import json
import os
import time

from consensus_tpu.backends import get_backend
from consensus_tpu.cli.run_experiment_with_eval import run_pipeline

CONFIG = os.environ.get(
    "PROFILE_CONFIG", "configs/north_star/gemma/scenario_1/habermas_vs_best_of_n.yaml"
)

import yaml  # noqa: E402


def main() -> None:
    with open(CONFIG) as f:
        config = yaml.safe_load(f)
    backend = get_backend(config.get("backend"), **(config.get("backend_options") or {}))

    calls = {"generate": [], "score": [], "embed": []}
    for kind in list(calls):
        orig = getattr(backend, kind)

        def timed(requests, _orig=orig, _kind=kind):
            t0 = time.perf_counter()
            out = _orig(requests)
            calls[_kind].append(
                {"rows": len(requests), "wall_s": round(time.perf_counter() - t0, 3)}
            )
            return out

        setattr(backend, kind, timed)

    overrides = {"output_dir": "/tmp/profile_combined"}
    extra = os.environ.get("PROFILE_OVERRIDES")
    if extra:
        overrides.update(json.loads(extra))
    t0 = time.perf_counter()
    run_dir = run_pipeline(
        CONFIG,
        skip_comparative_ranking=True,
        skip_llm_judge=True,
        config_overrides=overrides,
    )
    total = time.perf_counter() - t0

    summary = {"cell_wall_s": round(total, 1), "run_dir": str(run_dir)}
    for kind, entries in calls.items():
        summary[kind] = {
            "calls": len(entries),
            "rows": sum(e["rows"] for e in entries),
            "wall_s": round(sum(e["wall_s"] for e in entries), 1),
        }
    summary["token_counts"] = dict(getattr(backend, "token_counts", {}) or {})
    print(json.dumps(summary, indent=2))
    for kind, entries in calls.items():
        print(f"\n-- {kind} calls --")
        for e in entries:
            print(f"  rows={e['rows']:4d}  wall={e['wall_s']:9.3f}s")


if __name__ == "__main__":
    main()
