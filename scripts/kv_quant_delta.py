"""int8 generated-token KV: generation-side delta report (VERDICT r3 #1).

Round 4 makes int8 KV the segmented-decode default: the live tail is
written int8+scale (halving the while_loop carry the remote AOT compiler
copies every step) and frozen segment blocks stay int8.  Teacher-forced
scoring never reads generated KV, so every *metric* path is bit-unchanged
— the only thing int8 KV can move is WHICH tokens get generated.  This
script bounds that: decode the same prompts through the exact (bf16-KV)
and quantized paths with identical seeds and report

- greedy token agreement (and the first-divergence step distribution),
- the welfare-proxy delta: each variant's statements scored by the SAME
  exact scorer (per-row mean logprob under the reference prompt), so a
  systematic quality shift would show as a one-sided delta.

Weights are random (no checkpoint on the box); quantization noise is a
property of the numeric path, not the weight values' provenance.

Usage: PYTHONPATH=/root/.axon_site:/root/repo python scripts/kv_quant_delta.py
       [--quick]   (--quick: tiny model, CPU-ok)
"""

from __future__ import annotations

import argparse
import json
import pathlib
from datetime import datetime

import numpy as np

from consensus_tpu.backends.base import GenerationRequest, ScoreRequest
from consensus_tpu.backends.tpu import TPUBackend
from consensus_tpu.data.aamas_scenarios import SCENARIOS


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="gemma2-2b")
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--rows", type=int, default=16)
    parser.add_argument("--max-tokens", type=int, default=512)
    args = parser.parse_args()

    if args.quick:
        model, max_context, seg_len, max_tokens = "tiny-gemma2", 64, 16, 48
        dtype = "float32"
        quantization = None
    else:
        model, max_context, seg_len = args.model, 1024, 128
        max_tokens = args.max_tokens
        dtype = "bfloat16"
        quantization = "int8"

    scenario = SCENARIOS[1]
    opinions = "\n".join(
        f"{name}: {text}" for name, text in scenario["agent_opinions"].items()
    )
    prompt = (
        f"Issue: {scenario['issue']}\n\nOpinions:\n{opinions}\n\n"
        "Write one consensus statement that everyone can accept."
    )

    def make_backend(kv_quant: bool, donor: TPUBackend = None) -> TPUBackend:
        return TPUBackend(
            model=model,
            dtype=dtype,
            quantization=quantization,
            max_context=max_context,
            base_seed=0,
            use_flash_attention=not args.quick,
            decode_segment_len=seg_len,
            kv_quant=kv_quant,
            # Share the initialized weight tree: a second init+quantize
            # costs minutes against the tunneled chip and the comparison
            # REQUIRES identical weights anyway.
            params=donor.params if donor is not None else None,
            config=donor.config if donor is not None else None,
        )

    def decode(backend: TPUBackend, greedy: bool):
        requests = [
            GenerationRequest(
                user_prompt=prompt,
                max_tokens=max_tokens,
                temperature=0.0 if greedy else 1.0,
                seed=1000 + i,
            )
            for i in range(args.rows)
        ]
        results = backend.generate(requests)
        # Welfare proxy: score each statement under the exact scorer (the
        # scorer itself never touches generated KV, so it is shared).
        scores = backend.score(
            [
                ScoreRequest(context=prompt, continuation=r.text or " ")
                for r in results
            ]
        )
        return (
            [list(r.token_ids) for r in results],
            [s.mean() for s in scores],
        )

    report = {"generated": datetime.now().isoformat(timespec="seconds"),
              "model": model, "rows": args.rows, "max_tokens": max_tokens}
    arms = {}
    # One backend per KV mode, shared across arms: a fresh backend pays
    # minutes of host-side weight init against the tunneled chip.
    backend_exact = make_backend(False)
    backend_quant = make_backend(True, donor=backend_exact)
    for greedy in (True, False):
        exact_toks, exact_scores = decode(backend_exact, greedy)
        quant_toks, quant_scores = decode(backend_quant, greedy)
        agree, first_div, lengths = [], [], []
        for a, b in zip(exact_toks, quant_toks):
            n = max(len(a), len(b), 1)
            width = min(len(a), len(b))
            same = [x == y for x, y in zip(a, b)]
            agree.append((sum(same) + 0.0) / n)
            div = next((i for i, s in enumerate(same) if not s), None)
            first_div.append(div if div is not None else width)
            lengths.append(n)
        arms["greedy" if greedy else "sampled"] = {
            "token_agreement": float(np.mean(agree)),
            "median_first_divergence_step": float(np.median(first_div)),
            "mean_len": float(np.mean(lengths)),
            "exact_mean_logprob": float(np.mean(exact_scores)),
            "quant_mean_logprob": float(np.mean(quant_scores)),
            "welfare_proxy_delta": float(
                np.mean(quant_scores) - np.mean(exact_scores)
            ),
        }
    report["arms"] = arms

    out_dir = pathlib.Path("reports")
    out_dir.mkdir(exist_ok=True)
    (out_dir / "kv_quant_delta.json").write_text(json.dumps(report, indent=2))
    g, s = arms["greedy"], arms["sampled"]
    md = f"""# int8 generated-KV delta (production segmented-decode default)

- Generated: {report['generated']}  |  model: {model}  |  rows: {args.rows} x {max_tokens} tokens
- Scoring/welfare metrics are BIT-UNCHANGED by int8 KV (teacher forcing
  never reads generated KV); this measures the only affected surface —
  which tokens get generated — plus a welfare proxy (same-scorer mean
  logprob of each variant's statements).

| arm | token agreement | median first divergence step | exact mean logprob | int8-KV mean logprob | welfare-proxy delta |
|---|---|---|---|---|---|
| greedy | {g['token_agreement']:.1%} | {g['median_first_divergence_step']:.0f} | {g['exact_mean_logprob']:.4f} | {g['quant_mean_logprob']:.4f} | {g['welfare_proxy_delta']:+.4f} |
| sampled (T=1) | {s['token_agreement']:.1%} | {s['median_first_divergence_step']:.0f} | {s['exact_mean_logprob']:.4f} | {s['quant_mean_logprob']:.4f} | {s['welfare_proxy_delta']:+.4f} |

Sampled-arm agreement is expected to be low-ish in absolute terms — a
single changed sample step reroutes the whole suffix; the quantity that
matters is the welfare proxy staying within noise of the exact path.
"""
    (out_dir / "kv_quant_delta.md").write_text(md)
    print(md)


if __name__ == "__main__":
    main()
