"""Microbench: shared-trunk decode step time vs batch / quant / sampling.

The habermas cell profile (scripts/profile_habermas_cell.py) shows the
64-row x 768-step shared-trunk decode dispatch running at ~44 ms/step
against a ~6.5 ms HBM roofline (int8 weights 2.6 GB + avg tail KV ~2.6 GB
+ trunk 0.1 GB at 820 GB/s).  This script isolates the per-step cost
drivers by timing generate_tokens_shared_trunk with pinned budget (no
early exit) across arms:

- batch in {8, 32, 64}
- int8 vs bf16 weights
- greedy-ish sampling (top_k=1) vs full categorical (the production arm)
- short vs long tails (max_new 128 vs 768)

Usage: PYTHONPATH=/root/.axon_site:/root/repo python scripts/decode_step_bench.py
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from consensus_tpu.models.config import get_model_config
from consensus_tpu.models.generate import generate_tokens_shared_trunk
from consensus_tpu.models.quant import quantize_params
from consensus_tpu.models.transformer import init_params

CTX = 1024
MODEL = "gemma2-2b"


def run_segmented_arm(params, config, batch, max_new, seg_len, label,
                      kv_quant=False):
    from consensus_tpu.models.generate import (
        generate_tokens_shared_trunk_segmented,
    )

    tokens = np.zeros((1, CTX), np.int32)
    valid = np.ones((1, CTX), bool)
    keys = jax.vmap(lambda i: jax.random.fold_in(jax.random.PRNGKey(0), i))(
        jnp.arange(batch)
    )
    args = dict(
        batch=batch,
        key=keys,
        max_new_tokens=max_new,
        seg_len=seg_len,
        temperature=jnp.ones((batch,), jnp.float32),
        eos_ids=jnp.asarray([-1], jnp.int32),
        pad_id=0,
        kv_quant=kv_quant,
    )
    out = generate_tokens_shared_trunk_segmented(
        params, config, jnp.asarray(tokens), jnp.asarray(valid), **args
    )
    np.asarray(out.tokens)
    t0 = time.perf_counter()
    out = generate_tokens_shared_trunk_segmented(
        params, config, jnp.asarray(tokens), jnp.asarray(valid), **args
    )
    np.asarray(out.tokens)
    wall = time.perf_counter() - t0
    print(
        f"{label:44s} B={batch:3d} T={max_new:4d} "
        f"wall={wall:7.2f}s  {1000 * wall / max_new:7.2f} ms/step"
    )


def run_arm(params, config, batch, max_new, top_k, label):
    tokens = np.zeros((1, CTX), np.int32)
    valid = np.ones((1, CTX), bool)
    keys = jax.vmap(lambda i: jax.random.fold_in(jax.random.PRNGKey(0), i))(
        jnp.arange(batch)
    )
    args = dict(
        batch=batch,
        key=keys,
        max_new_tokens=max_new,
        temperature=jnp.ones((batch,), jnp.float32),
        eos_ids=jnp.asarray([-1], jnp.int32),  # pinned: no early exit
        pad_id=0,
    )
    if top_k:
        args["top_k"] = top_k
    out = generate_tokens_shared_trunk(
        params, config, jnp.asarray(tokens), jnp.asarray(valid), **args
    )
    np.asarray(out.tokens)  # force through the tunnel
    t0 = time.perf_counter()
    out = generate_tokens_shared_trunk(
        params, config, jnp.asarray(tokens), jnp.asarray(valid), **args
    )
    np.asarray(out.tokens)
    wall = time.perf_counter() - t0
    print(
        f"{label:44s} B={batch:3d} T={max_new:4d} "
        f"wall={wall:7.2f}s  {1000 * wall / max_new:7.2f} ms/step"
    )


def main() -> None:
    config = get_model_config(MODEL)
    params_bf16 = init_params(config, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    params_int8 = quantize_params(params_bf16)
    del params_bf16  # holding both param sets + the tail OOMs a 16 GB chip

    import os

    arms = os.environ.get("BENCH_ARMS", "all")
    if arms in ("all", "mono"):
        run_arm(params_int8, config, 64, 768, 0, "int8, categorical (production)")
        run_arm(params_int8, config, 64, 768, 1, "int8, top_k=1")
        run_arm(params_int8, config, 32, 768, 0, "int8, categorical")
        run_arm(params_int8, config, 8, 768, 0, "int8, categorical")
        run_arm(params_int8, config, 64, 128, 0, "int8, categorical, short tail")
        run_arm(params_int8, config, 1, 128, 0, "int8, categorical, B=1")
    if arms in ("all", "seg"):
        run_segmented_arm(params_int8, config, 64, 768, 128, "int8, SEGMENTED s=128")
        run_segmented_arm(params_int8, config, 64, 768, 96, "int8, SEGMENTED s=96")
        # Round 3's frozen-concat transient OOMed raw B=96 at T=768; the
        # round-4 block-list design (no concat) lifts the bf16 allowance to
        # ~96 and the int8-KV allowance to ~192 on a 16 GB chip.
        run_segmented_arm(params_int8, config, 48, 768, 128, "int8, SEGMENTED s=128")
    if arms in ("all", "kvq"):
        run_segmented_arm(params_int8, config, 64, 768, 128,
                          "int8, SEGMENTED s=128, int8 KV", kv_quant=True)
        run_segmented_arm(params_int8, config, 96, 768, 128,
                          "int8, SEGMENTED s=128, int8 KV", kv_quant=True)
    if arms == "r4c":
        # Classic layout (per-row prompt trunks — habermas ranking/critique
        # phases): the B x ctx trunk is the dominant per-step read; under
        # kv_quant it is int8 after prefill.
        from consensus_tpu.models.generate import generate_tokens_segmented

        def run_classic(batch, kv_quant, label):
            tokens = np.asarray(
                jax.random.randint(
                    jax.random.PRNGKey(2), (batch, CTX), 1, 255, jnp.int32
                )
            )
            valid = np.ones((batch, CTX), bool)
            keys = jax.vmap(
                lambda i: jax.random.fold_in(jax.random.PRNGKey(0), i)
            )(jnp.arange(batch))
            args = dict(
                key=keys, max_new_tokens=768, seg_len=128,
                temperature=jnp.ones((batch,), jnp.float32),
                eos_ids=jnp.asarray([-1], jnp.int32), pad_id=0,
                kv_quant=kv_quant,
            )
            out = generate_tokens_segmented(
                params_int8, config, jnp.asarray(tokens), jnp.asarray(valid), **args
            )
            np.asarray(out.tokens)
            t0 = time.perf_counter()
            out = generate_tokens_segmented(
                params_int8, config, jnp.asarray(tokens), jnp.asarray(valid), **args
            )
            np.asarray(out.tokens)
            wall = time.perf_counter() - t0
            print(
                f"{label:44s} B={batch:3d} T= 768 "
                f"wall={wall:7.2f}s  {1000 * wall / 768:7.2f} ms/step"
            )

        run_classic(32, False, "int8, CLASSIC SEGMENTED s=128")
        run_classic(32, True, "int8, CLASSIC SEGMENTED s=128, int8 KV+trunk")
        run_classic(48, True, "int8, CLASSIC SEGMENTED s=128, int8 KV+trunk")
    if arms == "r4":
        # Round-4 arms: per-ROW throughput is the metric that moves the
        # sweep (weights amortize over rows); the block-list + int8-tail
        # allowance admits 192 rows at the 768 budget.
        run_segmented_arm(params_int8, config, 64, 768, 128,
                          "int8, SEGMENTED s=128 (r4 blocks)")
        run_segmented_arm(params_int8, config, 64, 768, 128,
                          "int8, SEGMENTED s=128, int8 KV", kv_quant=True)
        run_segmented_arm(params_int8, config, 96, 768, 128,
                          "int8, SEGMENTED s=128, int8 KV", kv_quant=True)
        run_segmented_arm(params_int8, config, 128, 768, 128,
                          "int8, SEGMENTED s=128, int8 KV", kv_quant=True)
        run_segmented_arm(params_int8, config, 192, 768, 128,
                          "int8, SEGMENTED s=128, int8 KV", kv_quant=True)
    if arms in ("all", "bf16"):
        del params_int8
        params_bf16 = init_params(config, jax.random.PRNGKey(1), dtype=jnp.bfloat16)
        run_arm(params_bf16, config, 32, 768, 0, "bf16, categorical")


if __name__ == "__main__":
    main()
