"""Validate the habermas retry-elision premise on hardware (ADVICE r4).

``methods/habermas.py`` elides temperature-0 ranking retries on backends
whose greedy decode is argmax: the retry would replay the identical
response.  The elided retry, however, would have run in a DIFFERENT batch
composition (fewer pending rows, possibly another padding bucket) than
attempt 0 — so the elision additionally assumes greedy argmax is invariant
to batch width on the real device, which XLA does not promise in general
(accumulation order may differ across shapes).

This script tests exactly that: the same greedy request decoded at batch
widths 1, 8, 9, 32, and 64 (padded with distinct sibling prompts, target
row first/last), asserting token-identical output across all compositions.
Writes ``reports/greedy_batch_invariance.md`` + ``.json``.

Usage: PYTHONPATH=/root/.axon_site:/root/repo \
           python scripts/greedy_batch_invariance_check.py
       [--quick]          (--quick: tiny model, CPU-ok)
       [--backend fake]   (no model at all: deterministic fake backend —
                           exercises the harness end-to-end and pins the
                           fake's own composition invariance; jax-free)
"""

from __future__ import annotations

import argparse
import json
import pathlib
from datetime import datetime

from consensus_tpu.backends.base import GenerationRequest
from consensus_tpu.data.aamas_scenarios import SCENARIOS


def build_backend(args):
    """Returns (backend, model_label, dtype, quantization, max_tokens)."""
    if args.backend == "fake":
        from consensus_tpu.backends.fake import FakeBackend

        return FakeBackend(), "fake", "none", None, min(args.max_tokens, 32)
    from consensus_tpu.backends.tpu import TPUBackend

    if args.quick:
        import jax

        jax.config.update("jax_platforms", "cpu")
        model, max_context, max_tokens = "tiny-gemma2", 256, 32
        dtype, quantization = "float32", None
    else:
        model, max_context = args.model, 1024
        max_tokens = args.max_tokens
        dtype, quantization = "bfloat16", "int8"

    backend = TPUBackend(
        model=model,
        dtype=dtype,
        quantization=quantization,
        max_context=max_context,
        base_seed=0,
        use_flash_attention=not args.quick,
    )
    return backend, model, dtype, quantization, max_tokens


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="gemma2-2b")
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--max-tokens", type=int, default=256)
    parser.add_argument(
        "--backend", choices=["tpu", "fake"], default="tpu",
        help="'fake' runs the identical harness on the deterministic fake "
        "backend (no jax, no weights) — CI-runnable end-to-end check.",
    )
    parser.add_argument(
        "--report-dir", default="reports",
        help="Directory for greedy_batch_invariance.{md,json}.",
    )
    args = parser.parse_args()

    backend, model, dtype, quantization, max_tokens = build_backend(args)

    scenario = SCENARIOS[1]
    opinions = list(scenario["agent_opinions"].values())
    target = (
        f"Issue: {scenario['issue']}\n\nOpinion: {opinions[0]}\n\n"
        "Rank the candidate statements from best to worst."
    )
    siblings = [
        f"Issue: {scenario['issue']}\n\nOpinion: {opinions[i % len(opinions)]}\n\n"
        f"Sibling prompt variant {i}: write a consensus statement."
        for i in range(63)
    ]

    def run(width: int, target_pos: int) -> str:
        prompts = list(siblings[: width - 1])
        prompts.insert(target_pos, target)
        requests = [
            GenerationRequest(
                user_prompt=p, max_tokens=max_tokens, temperature=0.0, seed=7
            )
            for p in prompts
        ]
        results = backend.generate(requests)
        return results[target_pos].text

    # Widths must STRADDLE padding-bucket boundaries, not just vary inside
    # one bucket: tpu.py buckets rows (minimum 8), so widths 1 and 4 would
    # execute the identical 8-row program.  1/8 share the smallest bucket;
    # 9 forces the next one; 32/64 are the shapes real sweep batches
    # (max_batch_rows up to 64) actually run — the compositions an elided
    # habermas retry would have landed in.
    compositions = [(1, 0), (8, 0), (9, 8), (32, 0), (32, 31), (64, 63)]
    outputs = {}
    for width, pos in compositions:
        key = f"width={width},pos={pos}"
        outputs[key] = run(width, pos)
        print(f"{key}: {len(outputs[key])} chars")

    baseline = outputs["width=1,pos=0"]
    mismatches = {k: v != baseline for k, v in outputs.items()}
    invariant = not any(mismatches.values())

    payload = {
        "generated": datetime.now().isoformat(timespec="seconds"),
        "backend": args.backend,
        "model": model,
        "dtype": dtype,
        "quantization": quantization,
        "max_tokens": max_tokens,
        "compositions": [f"width={w},pos={p}" for w, p in compositions],
        "token_identical": invariant,
        "mismatching_compositions": [k for k, bad in mismatches.items() if bad],
    }
    reports = pathlib.Path(args.report_dir)
    reports.mkdir(parents=True, exist_ok=True)
    (reports / "greedy_batch_invariance.json").write_text(
        json.dumps(payload, indent=2)
    )
    lines = [
        "# Greedy batch-composition invariance (habermas retry-elision premise)",
        "",
        f"- Generated: {payload['generated']}",
        f"- Backend: {args.backend}",
        f"- Model: {model} ({dtype}, quant={quantization}), greedy, "
        f"{max_tokens} tokens",
        "- Premise under test: argmax decode is invariant to batch width / "
        "row position, so a temperature-0 retry in a smaller batch would "
        "replay attempt 0 exactly (`methods/habermas.py` retry elision).",
        "",
        f"Result: **{'INVARIANT' if invariant else 'NOT invariant'}** across "
        f"compositions {', '.join(payload['compositions'])}.",
    ]
    if not invariant:
        lines += [
            "",
            "Mismatching compositions: "
            + ", ".join(payload["mismatching_compositions"]),
            "",
            "ACTION: the retry-elision `break` in "
            "`consensus_tpu/methods/habermas.py` rests on a premise this "
            "hardware violates — remove it or gate it per-model.",
        ]
    (reports / "greedy_batch_invariance.md").write_text("\n".join(lines) + "\n")
    print(f"token_identical={invariant}")


if __name__ == "__main__":
    main()
