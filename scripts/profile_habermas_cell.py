"""Profile one north-star habermas_only cell at device-dispatch granularity.

Round-3 continuation: the habermas-family cells dominate the timed sweep
(~65 of 92 min), yet a roofline estimate of their decode work is several
times smaller than the measured cell wall.  This script runs the exact
scenario-1 habermas_only cell (30 runs: nc {2,5,10} x rounds {1,2} x 5
seeds) with instrumentation on every level of the stack:

- BatchingBackend flushes (merged request counts per flush)
- TPUBackend.generate calls (rows, wall)
- generate_tokens_shared_trunk / generate_tokens device dispatches
  (rows, prompt width, max_new, wall)

so the gap between "roofline decode time" and "measured cell wall" is
attributed instead of guessed.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np
import yaml

import consensus_tpu.models.generate as gen_mod
from consensus_tpu.backends import get_backend
from consensus_tpu.experiment import Experiment

CONFIG = os.environ.get("PROFILE_CONFIG", "configs/north_star/gemma/scenario_1/habermas_only.yaml")

dispatches = []


def wrap_dispatch(name, fn):
    def wrapped(params, config, prompt_tokens, prompt_valid, *args, **kwargs):
        t0 = time.perf_counter()
        out = fn(params, config, prompt_tokens, prompt_valid, *args, **kwargs)
        np.asarray(out.tokens)  # force through the tunnel (np fields: no-op)
        wall = time.perf_counter() - t0
        if name.startswith("shared"):
            batch = args[0]
        else:
            batch = prompt_tokens.shape[0]
        max_new = kwargs.get("max_new_tokens", "?")
        dispatches.append(
            {
                "kind": name,
                "rows": int(batch),
                "ctx_width": int(prompt_tokens.shape[1]),
                "max_new": max_new,
                "wall_s": round(wall, 3),
            }
        )
        return out

    return wrapped


gen_mod.generate_tokens_shared_trunk = wrap_dispatch(
    "shared", gen_mod.generate_tokens_shared_trunk
)
gen_mod.generate_tokens = wrap_dispatch("classic", gen_mod.generate_tokens)
# Segmented entry points (the default for long budgets) are whole host
# loops, not single dispatches — timed the same way for attribution.
gen_mod.generate_tokens_shared_trunk_segmented = wrap_dispatch(
    "shared-seg", gen_mod.generate_tokens_shared_trunk_segmented
)
gen_mod.generate_tokens_segmented = wrap_dispatch(
    "classic-seg", gen_mod.generate_tokens_segmented
)
# tpu.py binds generate_tokens at module import; patch its reference too.
import consensus_tpu.backends.tpu as tpu_mod  # noqa: E402

tpu_mod.generate_tokens = gen_mod.generate_tokens


def main() -> None:
    with open(CONFIG) as f:
        config = yaml.safe_load(f)

    if os.environ.get("PROFILE_PIN"):
        # Mirror run_sweep --timing-pin-budget in full: the method-side
        # pin_budget half is injected by Experiment._run_configs from this
        # flag, and the backend-side pin_generation_budget half (device
        # EOS early-exit disabled) is applied to the explicit backend below.
        config["timing_pin_budget"] = True

    backend_opts = dict(config.get("backend_options") or {})
    if config.get("timing_pin_budget") and config.get("backend") == "tpu":
        backend_opts["pin_generation_budget"] = True
    backend = get_backend(config.get("backend"), **backend_opts)

    # Instrument the inner generate (what each Batching flush calls).
    inner_calls = []
    orig_generate = backend.generate

    def timed_generate(requests):
        t0 = time.perf_counter()
        out = orig_generate(requests)
        inner_calls.append(
            {"rows": len(requests), "wall_s": round(time.perf_counter() - t0, 3)}
        )
        return out

    backend.generate = timed_generate

    config["output_dir"] = "/tmp/profile_habermas"
    t0 = time.perf_counter()
    experiment = Experiment(config, backend=backend)
    frame = experiment.run()
    total = time.perf_counter() - t0

    gen_time = sum(d["wall_s"] for d in dispatches)
    inner_time = sum(c["wall_s"] for c in inner_calls)
    print(json.dumps({
        "cell_wall_s": round(total, 1),
        "statements": len(frame),
        "device_dispatches": len(dispatches),
        "device_dispatch_s": round(gen_time, 1),
        "inner_generate_calls": len(inner_calls),
        "inner_generate_s": round(inner_time, 1),
        "host_overhead_s": round(total - inner_time, 1),
        "tokenize_etc_s": round(inner_time - gen_time, 1),
        "batch_counts": getattr(experiment, "last_batch_counts", None),
        "token_counts": dict(getattr(backend, "token_counts", {}) or {}),
    }, indent=2))
    print("\n-- inner generate calls (rows, wall) --")
    for c in inner_calls:
        print(f"  rows={c['rows']:4d}  wall={c['wall_s']:8.3f}s")
    print("\n-- device dispatches --")
    for d in dispatches:
        print(
            f"  {d['kind']:8s} rows={d['rows']:4d} ctx={d['ctx_width']:5d} "
            f"max_new={d['max_new']} wall={d['wall_s']:8.3f}s"
        )


if __name__ == "__main__":
    main()
