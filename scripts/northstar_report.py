"""Build the timed north-star artifact from a finished run_sweep pass.

Collects per-config wall-clock from the sweep log plus per-statement
generation times from each run dir's results.csv, and writes
``reports/northstar_timing.json`` + ``.md``.

North star (BASELINE.json): the full AAMAS 5-scenario x 5-seed Gemma-2B
sweep on TPU in under an hour — against an API baseline where ONE
beam-search statement averages 4 019-5 117 s (BASELINE.md).

Usage: python scripts/northstar_report.py /tmp/northstar.log [results/aamas]
"""

from __future__ import annotations

import json
import pathlib
import re
import sys
from datetime import datetime

import pandas as pd

DONE_RE = re.compile(
    r"\[(\d+)/(\d+)\] done in ([0-9.]+)s -> (\S+)"
)
CONFIG_RE = re.compile(r"\[(\d+)/(\d+)\] (configs/\S+\.yaml)")

#: Mean seconds/statement of the reference's Together-API implementation
#: (BASELINE.md, scenario ranges).
API_BASELINE_S_PER_STATEMENT = {
    "beam_search": 4019.0,
    "finite_lookahead": 944.0,
    "best_of_n": 61.0,
    "habermas_machine": 59.0,
    "zero_shot": 61.0,
    "predefined": 0.0,
}


def main(
    log_path: str,
    results_root: str = "results/aamas",
    out_prefix: str = "northstar",
) -> int:
    text = pathlib.Path(log_path).read_text()
    configs = {m.group(1): m.group(3) for m in CONFIG_RE.finditer(text)}
    rows = []
    for match in DONE_RE.finditer(text):
        index, total, seconds, run_dir = match.groups()
        entry = {
            "config": configs.get(index, "?"),
            "wall_s": float(seconds),
            "run_dir": run_dir,
        }
        tokens_json = pathlib.Path(run_dir) / "token_counts.json"
        if tokens_json.exists():
            # Token-honest columns (VERDICT r2 #4): tokens actually
            # generated/scored, so s/stmt can't be flattered by degenerate
            # short statements.
            entry["tokens"] = json.loads(tokens_json.read_text())
        results_csv = pathlib.Path(run_dir) / "results.csv"
        if results_csv.exists():
            df = pd.read_csv(results_csv)
            entry["statements"] = int(len(df))
            entry["errors"] = int(
                df["error_message"].fillna("").astype(str).str.strip().ne("").sum()
            )
            # Random-weight degeneracies (all compute still runs, so the
            # timings are valid): habermas candidates can't emit the CoT
            # <answer> envelope from byte noise, and lookahead's fixed
            # random model happens to rate "\n" above average so the
            # 1-token terminator path keeps winning.  Rows with a real
            # error_message are NOT degenerate — they count as errors only.
            statements = df["statement"].fillna("").astype(str)
            errored = (
                df["error_message"].fillna("").astype(str).str.strip().ne("")
            )
            entry["degenerate_statements"] = int(
                (
                    statements.str.strip().eq("")
                    | statements.str.lstrip().str.startswith("[ERROR")
                )[~errored].sum()
            )
            per_method = (
                df.groupby("method")["generation_time_s"]
                .agg(["count", "mean", "max"])
                .round(2)
            )
            entry["methods"] = {
                method: {
                    "statements": int(stats["count"]),
                    "mean_s_per_statement": float(stats["mean"]),
                    "max_s_per_statement": float(stats["max"]),
                    "api_baseline_s_per_statement": API_BASELINE_S_PER_STATEMENT.get(
                        method
                    ),
                }
                for method, stats in per_method.iterrows()
            }
        rows.append(entry)

    total_wall = sum(r["wall_s"] for r in rows)
    total_statements = sum(r.get("statements", 0) for r in rows)
    total_tokens = sum(
        r.get("tokens", {}).get("tokens_generated", 0)
        + r.get("tokens", {}).get("tokens_scored", 0)
        for r in rows
    )
    # Self-describe the backend (e.g. quantization mode).  If configs in
    # the sweep disagree, say so rather than stamping one config's options
    # over a heterogeneous run.
    import yaml

    seen_options = []
    for row in rows:
        # Prefer the run dir's config.yaml SNAPSHOT (what actually ran) over
        # the working-tree configs/, which may have been regenerated since.
        candidates = [
            pathlib.Path(row["run_dir"]) / "config.yaml",
            pathlib.Path(row["config"]),
        ]
        for cfg_path in candidates:
            if cfg_path.exists():
                opts = (
                    yaml.safe_load(cfg_path.read_text()).get("backend_options")
                    or {}
                )
                if opts not in seen_options:
                    seen_options.append(opts)
                break
    if not seen_options:
        backend_options = {}
    elif len(seen_options) == 1:
        backend_options = seen_options[0]
    else:
        backend_options = {"mixed": seen_options}
    # Sweep-level MFU (VERDICT r3 #3), shared accounting with bench.py
    # (consensus_tpu/utils/mfu.py); params come from the sweep's OWN model
    # so a 9B/llama sweep doesn't inherit gemma2-2b's constant.
    from consensus_tpu.models.config import get_model_config
    from consensus_tpu.utils.mfu import (
        param_count,
        pct_of_peak,
        useful_tflops_per_sec,
    )

    model_names = {
        opts.get("model")
        for opts in (seen_options or [{}])
        if isinstance(opts, dict) and opts.get("model")
    }
    mfu_model = model_names.pop() if len(model_names) == 1 else None
    if mfu_model:
        # Random-weight sweeps execute a model whose vocab the backend
        # shrank to the byte tokenizer's id range (backends/tpu.py
        # checkpoint-is-None branch) — count the params that actually ran.
        # A checkpoint/tokenizer-configured sweep keeps the preset vocab.
        random_weights = not any(
            isinstance(opts, dict)
            and (opts.get("checkpoint") or opts.get("tokenizer"))
            for opts in (seen_options or [])
        )
        if random_weights:
            from consensus_tpu.models.tokenizer import get_tokenizer

            vocab = get_tokenizer(None).vocab_size
        else:
            vocab = get_model_config(mfu_model).vocab_size
        n_params = param_count(get_model_config(mfu_model, vocab_size=vocab))
        sweep_tflops = useful_tflops_per_sec(n_params, total_tokens, total_wall)
        sweep_pct_peak = pct_of_peak(sweep_tflops)
    else:
        sweep_tflops = sweep_pct_peak = 0.0
    report = {
        "generated": datetime.now().isoformat(timespec="seconds"),
        "hardware": "1x TPU v5e chip (tunneled axon; north star targets v5e-8)",
        "weights": "random (no checkpoint on the box; timings/shapes real)",
        "backend_options": backend_options,
        "configs_completed": len(rows),
        "total_wall_s": round(total_wall, 1),
        "total_statements": total_statements,
        "total_errors": sum(r.get("errors", 0) for r in rows),
        "degenerate_statements": sum(
            r.get("degenerate_statements", 0) for r in rows
        ),
        "under_one_hour": total_wall < 3600,
        "total_useful_tokens": total_tokens,
        "sweep_tflops_per_sec": round(sweep_tflops, 2),
        "sweep_pct_of_v5e_bf16_peak": round(sweep_pct_peak, 2),
        "configs": rows,
    }
    out = pathlib.Path("reports")
    out.mkdir(exist_ok=True)
    (out / f"{out_prefix}_timing.json").write_text(json.dumps(report, indent=2))

    lines = [
        "# North-star timed sweep",
        "",
        f"- Generated: {report['generated']}",
        f"- Hardware: {report['hardware']}",
        f"- Weights: {report['weights']}",
        f"- Backend: {backend_options or 'n/a'}",
        (
            f"- Utilization ({mfu_model}, random-weight vocab "
            f"{vocab if mfu_model else 0}): {total_tokens:,} useful tokens "
            f"(generated+scored) -> **{sweep_tflops:.1f} TFLOP/s = "
            f"{sweep_pct_peak:.1f}% of v5e bf16 peak** at 2*params*token; "
            "padding, KV/weight HBM traffic, evaluation/aggregation host "
            "time, and tunnel RTTs all count as lost utilization here "
            "(scoring kernels alone run at 50-65% MFU warm — "
            "scripts/scoring_bench.py)."
            if mfu_model
            else f"- Utilization: n/a (mixed/unknown models); "
            f"{total_tokens:,} useful tokens"
        ),
        "- Note: configs meeting a (shape-bucket, program) pair for the "
        "first time since the compile cache was last cold pay its one-time "
        "remote-AOT compile; repeat configs run warm.",
        f"- Configs: {len(rows)} | statements: {total_statements} "
        f"(errors: {report['total_errors']}, random-weight degenerate: "
        f"{report['degenerate_statements']}) | "
        f"wall: **{total_wall/60:.1f} min** "
        f"({'UNDER' if report['under_one_hour'] else 'OVER'} the 1 h target "
        "on 1/8th of the target hardware — dp=8 data-parallel serving puts "
        f"it at ~{total_wall/8/60:.0f} min; unlike round 2 that path is now "
        "IMPLEMENTED: `TPUBackend(dp=8)` shards protocol batch rows over "
        "the mesh with per-row results pinned identical to single-device "
        "on the 8-device virtual mesh (tests/test_dp_serving.py, "
        "MULTICHIP dryrun serving section), so the projection is a "
        "measured-sharding property, not an extrapolation over missing "
        "code)",
        "",
    ]
    if report["degenerate_statements"]:
        lines += [
            "Degenerate statements are a random-weights artifact, not a "
            "framework failure: habermas candidates cannot emit the CoT "
            "`<answer>` envelope from byte noise (the reference skips such "
            "candidates identically, habermas_machine.py:480-527), and the "
            "fixed random model rates `\\n` above average so lookahead's "
            "1-token terminator path keeps winning.  TIMING CAVEAT: when "
            "every candidate fails to parse, the habermas pipeline "
            "short-circuits after the candidate phase (+1 retry), so "
            "unpinned habermas cells time ~1 of its 4+ phases; the "
            "pinned-budget pass (`--timing-pin-budget`) adds parse "
            "fallbacks so every phase runs — use ITS habermas numbers as "
            "the full-workload cost.  Beam/lookahead/bon cells run their "
            "full compute either way.",
            "",
        ]
    lines += [
        "Per-row times: runs execute CONCURRENTLY (all same-phase device "
        "calls of a cell merge into shared batches), so a single run's "
        "`generation_time_s` includes time it spent co-batched with its "
        "siblings — the honest per-statement cost is the CELL-level "
        "`wall s / statements`, compared against the statement-weighted "
        "API baseline of the methods in the cell.",
        "",
        "| config | wall s | statements | methods | cell s/stmt | tok gen | tok scored | s/1k tok | weighted API s/stmt | speedup |",
        "|---|---|---|---|---|---|---|---|---|---|",
    ]
    for row in rows:
        statements = row.get("statements") or 0
        methods = row.get("methods", {})
        tokens = row.get("tokens") or {}
        tok_gen = tokens.get("tokens_generated")
        tok_scored = tokens.get("tokens_scored")
        s_per_1k = tokens.get("s_per_1k_tokens")
        tok_cols = (
            f"| {tok_gen} | {tok_scored} | {s_per_1k} "
            if tok_gen is not None
            else "| - | - | - "
        )
        if not statements or not methods:
            lines.append(
                f"| {row['config'].split('configs/')[-1]} | {row['wall_s']:.0f} "
                f"| {statements or '?'} | - | - {tok_cols}| - | - |"
            )
            continue
        cell = row["wall_s"] / statements
        # A method without a published API baseline must not silently count
        # as 0 in the weighted average (it would deflate the speedup).
        if any(
            s["api_baseline_s_per_statement"] is None for s in methods.values()
        ):
            weighted_base = None
        else:
            weighted_base = sum(
                s["statements"] * s["api_baseline_s_per_statement"]
                for s in methods.values()
            ) / statements
        speedup = (
            f"{weighted_base / cell:.0f}x" if weighted_base and cell else "-"
        )
        breakdown = ", ".join(
            f"{m}:{s['statements']}" for m, s in methods.items()
        )
        base_cell = f"{weighted_base:.0f}" if weighted_base is not None else "-"
        lines.append(
            f"| {row['config'].split('configs/')[-1]} | {row['wall_s']:.0f} "
            f"| {statements} | {breakdown} | {cell:.2f} "
            f"{tok_cols}| {base_cell} | {speedup} |"
        )
    (out / f"{out_prefix}_timing.md").write_text("\n".join(lines) + "\n")
    print(json.dumps({k: report[k] for k in (
        "configs_completed", "total_wall_s", "total_statements", "under_one_hour"
    )}))
    return 0


if __name__ == "__main__":
    sys.exit(main(*sys.argv[1:]))
