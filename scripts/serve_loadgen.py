#!/usr/bin/env python
"""Open-loop load generator CLI for the consensus server.

Replays AAMAS-scenario requests at a target rate and reports throughput,
p50/p95/p99 latency, and rejection rate (one JSON object on stdout).

Two modes:

* ``--url http://host:port`` — drive an already-running server.
* ``--self-contained`` — spin up an in-process fake-backend server (the
  hardware-free smoke path), drive it, and shut it down; prints the same
  report plus the server's device-batch accounting, which shows the
  coalescing win (merged device batches << per-request call count).

Examples:

    python scripts/serve_loadgen.py --self-contained --requests 32 --rate 50
    python scripts/serve_loadgen.py --url http://127.0.0.1:8080 \
        --requests 100 --rate 10 --method best_of_n --params '{"n": 8}'
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--url", default=None,
                        help="base URL of a running server")
    parser.add_argument("--self-contained", action="store_true",
                        help="start an in-process fake-backend server")
    parser.add_argument("--requests", type=int, default=32)
    parser.add_argument("--rate", type=float, default=20.0,
                        help="offered load, requests/sec (open loop)")
    parser.add_argument("--method", default="best_of_n")
    parser.add_argument("--params", default='{"n": 4, "max_tokens": 24}',
                        help="JSON object of method params")
    parser.add_argument("--seed", type=int, default=100)
    parser.add_argument("--scenario-repeat", default=None, metavar="MIX",
                        help="scenario arrival mix: 'fixed:K' cycles the "
                             "first K scenarios, 'zipf:S' draws ranks with "
                             "probability 1/(r+1)^S (default: round-robin "
                             "over all scenarios); repeated scenarios are "
                             "what the prefix KV cache accelerates, and "
                             "the report then shows prefix_hit_fraction")
    parser.add_argument("--agents", type=int, default=None, metavar="N",
                        help="expand every scenario to exactly N "
                             "deterministic opinion-holders (base opinions "
                             "cycled as variant-tagged panel members) — the "
                             "AAMAS 50-200 agent regime the utility-matrix "
                             "scoring path is sized for")
    parser.add_argument("--evaluate", action="store_true",
                        help="request per-agent utilities + welfare too")
    parser.add_argument("--timeout-s", type=float, default=None,
                        help="per-request deadline sent to the server")
    parser.add_argument("--client-timeout-s", type=float, default=60.0)
    parser.add_argument("--max-inflight", type=int, default=4,
                        help="(self-contained) worker pool size")
    parser.add_argument("--max-queue-depth", type=int, default=64,
                        help="(self-contained) admission queue bound")
    parser.add_argument("--engine", action="store_true",
                        help="(self-contained) serve through the "
                             "continuous-batching decode engine")
    parser.add_argument("--prefix-cache", action="store_true",
                        help="(self-contained) enable the engine's "
                             "cross-request prefix KV cache (implies "
                             "--engine)")
    parser.add_argument("--engine-options", default="{}",
                        help="(self-contained) JSON object of extra "
                             "DecodeEngine options (slots, num_pages, "
                             "prefix_cache_pages, ...)")
    parser.add_argument("--mesh", default=None, metavar="dp=N,tp=M",
                        help="(self-contained) serve over the (data, model) "
                             "device mesh: the decode engine partitions its "
                             "slots + page pools over dp (implies --engine) "
                             "and the report gains dp_shard_slot_occupancy")
    parser.add_argument("--brownout", action="store_true",
                        help="(self-contained) enable the brownout "
                             "controller: overloaded requests run at a "
                             "scaled search budget (degraded 200s) instead "
                             "of timing out")
    parser.add_argument("--target-p95-ms", type=float, default=None,
                        help="(self-contained) latency SLO fed into the "
                             "brownout pressure signal (implies --brownout)")
    parser.add_argument("--metrics-out", default=None,
                        help="write the serve-side registry snapshot delta "
                             "(metrics.json schema) here (self-contained)")
    parser.add_argument("--fleet", type=int, default=1, metavar="N",
                        help="(self-contained) run N backend replicas "
                             "behind the fleet router; the report gains "
                             "replica_request_counts and failover_fraction")
    parser.add_argument("--fleet-options", default="{}",
                        help="(self-contained) JSON object of fleet "
                             "options (tiers, hedge_after_s, ...)")
    parser.add_argument("--kill-replica-at-s", type=float, default=None,
                        metavar="S",
                        help="(self-contained, fleet) kill a replica S "
                             "seconds into the run: its backend starts "
                             "raising BackendLostError and in-flight "
                             "requests fail over")
    parser.add_argument("--kill-replica", default="r0", metavar="NAME",
                        help="(self-contained, fleet) which replica "
                             "--kill-replica-at-s kills (default: r0)")
    parser.add_argument("--fault-plan", default=None,
                        help="(self-contained) JSON fault plan injected "
                             "below a supervised backend, e.g. "
                             '\'{"seed": 7, "faults": [{"kind": '
                             '"transient_error", "rate": 0.05}]}\'; the '
                             "report gains availability and retried "
                             "fraction")
    args = parser.parse_args(argv)
    if bool(args.url) == bool(args.self_contained):
        parser.error("exactly one of --url / --self-contained is required")

    from consensus_tpu.serve.loadgen import (
        report_json,
        run_loadgen,
        scenario_requests,
    )

    payloads = scenario_requests(
        args.requests,
        method=args.method,
        params=json.loads(args.params),
        base_seed=args.seed,
        evaluate=args.evaluate,
        timeout_s=args.timeout_s,
        scenario_repeat=args.scenario_repeat,
        agents=args.agents,
    )

    if args.self_contained:
        from consensus_tpu.obs import diff_snapshots, get_registry
        from consensus_tpu.serve import create_server
        from consensus_tpu.utils.io_atomic import atomic_write_json

        engine_options = json.loads(args.engine_options) or {}
        if args.prefix_cache:
            engine_options.setdefault("prefix_cache", True)
        server = create_server(
            backend="fake",
            port=0,  # ephemeral
            max_inflight=args.max_inflight,
            max_queue_depth=args.max_queue_depth,
            fault_plan=args.fault_plan,
            brownout=args.brownout or args.target_p95_ms is not None,
            target_p95_ms=args.target_p95_ms,
            engine=args.engine or args.prefix_cache or bool(engine_options)
            or args.mesh is not None,
            engine_options=engine_options or None,
            fleet_size=args.fleet,
            fleet_options=json.loads(args.fleet_options) or None,
            mesh=args.mesh,
        ).start()
        killer = None
        if args.kill_replica_at_s is not None:
            if args.fleet <= 1:
                parser.error("--kill-replica-at-s needs --fleet > 1")
            import threading

            killer = threading.Timer(
                args.kill_replica_at_s,
                server.scheduler.kill_replica,
                args=(args.kill_replica,),
            )
            killer.daemon = True
        before = get_registry().snapshot()
        try:
            if killer is not None:
                killer.start()
            report = run_loadgen(
                server.base_url, payloads, args.rate,
                client_timeout_s=args.client_timeout_s,
            )
            report["device_batches"] = server.scheduler.stats()[
                "device_batches"]
        finally:
            if killer is not None:
                killer.cancel()
            server.stop()
        delta = diff_snapshots(before, get_registry().snapshot())

        def family_total(name):
            family = (delta.get("families") or {}).get(name) or {}
            return sum(s.get("value", 0) for s in family.get("series", []))

        # Retries absorbed below the HTTP surface: supervisor-level call
        # retries plus scheduler-level ticket retries, per offered request.
        retries = family_total("supervisor_retries_total") + family_total(
            "serve_retried_total")
        report["retried_fraction"] = (
            round(retries / args.requests, 4) if args.requests else 0.0)
        if args.metrics_out:
            payload = {"schema": "consensus_tpu.metrics.v1",
                       "metrics": delta}
            atomic_write_json(pathlib.Path(args.metrics_out), payload)
    else:
        report = run_loadgen(
            args.url, payloads, args.rate,
            client_timeout_s=args.client_timeout_s,
        )

    print(report_json(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
