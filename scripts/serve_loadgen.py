#!/usr/bin/env python
"""Open-loop load generator CLI for the consensus server.

Replays AAMAS-scenario requests at a target rate and reports throughput,
p50/p95/p99 latency, and rejection rate (one JSON object on stdout).

Two modes:

* ``--url http://host:port`` — drive an already-running server.
* ``--self-contained`` — spin up an in-process fake-backend server (the
  hardware-free smoke path), drive it, and shut it down; prints the same
  report plus the server's device-batch accounting, which shows the
  coalescing win (merged device batches << per-request call count).

Examples:

    python scripts/serve_loadgen.py --self-contained --requests 32 --rate 50
    python scripts/serve_loadgen.py --url http://127.0.0.1:8080 \
        --requests 100 --rate 10 --method best_of_n --params '{"n": 8}'
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def _parse_chaos_schedule(spec):
    """``'kill:10,kill:25:r1,restart:40'`` -> ``[(10.0, 'kill', None),
    (25.0, 'kill', 'r1'), (40.0, 'restart', None)]``, sorted by fire
    time.  ``kill`` takes an optional replica NAME; ``restart`` rolls
    the whole fleet and takes none."""
    events = []
    for item in str(spec).split(","):
        item = item.strip()
        if not item:
            continue
        parts = item.split(":")
        if parts[0] == "kill" and len(parts) in (2, 3):
            events.append((float(parts[1]), "kill",
                           parts[2] if len(parts) == 3 else None))
        elif parts[0] == "restart" and len(parts) == 2:
            events.append((float(parts[1]), "restart", None))
        else:
            raise ValueError(
                "chaos event must be 'kill:S', 'kill:S:NAME' or "
                f"'restart:S', got {item!r}")
    events.sort(key=lambda e: e[0])
    return events


def _run_chaos(router, schedule, recover_timeout_s, events_out, stop):
    """Fire ``schedule`` against a live FleetRouter and measure, per kill,
    how long the fleet takes to read fully healthy again (the elastic
    manager's detect -> respawn -> warm-seed -> rejoin round trip).  Runs
    on its own daemon thread alongside the open-loop load."""
    import time

    start = time.monotonic()
    for index, (at_s, kind, name) in enumerate(schedule):
        if stop.wait(max(0.0, start + at_s - time.monotonic())):
            return
        if kind == "restart":
            # Rolling restart of the whole fleet: drain -> capture ->
            # respawn -> warm-seed -> health-gated rejoin, one replica
            # at a time.  rolling_restart() is synchronous, so its
            # return doubles as the recovery point.
            event = {"kind": kind, "at_s": at_s, "replica": None,
                     "recovered_s": None}
            events_out.append(event)
            manager = getattr(router, "manager", None)
            if manager is None:
                continue
            fired = time.monotonic()
            outcome = manager.rolling_restart()
            event["restarted"] = outcome.get("restarted")
            event["aborted"] = outcome.get("aborted")
            if outcome.get("aborted") is None:
                event["recovered_s"] = round(time.monotonic() - fired, 3)
            continue
        target = name
        if target is None:
            live = [r.name for r in router.replicas if not r.lost]
            target = live[0] if live else None
        event = {"kind": kind, "at_s": at_s, "replica": target,
                 "recovered_s": None}
        events_out.append(event)
        if target is None:
            continue
        size_before = len(router.replicas)
        try:
            router.kill_replica(target, reason="chaos")
        except KeyError:
            continue
        # Poll until the fleet is back at its pre-kill size with every
        # member HEALTHY (corpse removal shrinks size mid-recovery, so
        # healthy == size alone would declare victory too early), bounded
        # by the recovery timeout and by the next scheduled event.
        fired = time.monotonic()
        deadline = fired + recover_timeout_s
        if index + 1 < len(schedule):
            deadline = min(deadline, start + schedule[index + 1][0])
        while time.monotonic() < deadline and not stop.is_set():
            fleet = router.stats().get("fleet") or {}
            if (fleet.get("size", 0) >= size_before
                    and fleet.get("healthy", 0) >= fleet.get("size", 0)):
                event["recovered_s"] = round(time.monotonic() - fired, 3)
                break
            if stop.wait(0.05):
                return


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--url", default=None,
                        help="base URL of a running server")
    parser.add_argument("--self-contained", action="store_true",
                        help="start an in-process fake-backend server")
    parser.add_argument("--requests", type=int, default=32)
    parser.add_argument("--rate", type=float, default=20.0,
                        help="offered load, requests/sec (open loop)")
    parser.add_argument("--method", default="best_of_n")
    parser.add_argument("--params", default='{"n": 4, "max_tokens": 24}',
                        help="JSON object of method params")
    parser.add_argument("--seed", type=int, default=100)
    parser.add_argument("--corpus", default=None, metavar="NAME[:MIX]",
                        help="drive load from a scenario corpus instead "
                             "of the 5 AAMAS scenarios: NAME resolves "
                             "via the scenario registry ('v2' -> "
                             "data/scenarios_v2, or a directory path); "
                             "an optional :MIX weights families, e.g. "
                             "'v2:polarized=2,sybil=1'.  Per-request "
                             "assignment is deterministic in --seed, and "
                             "the report's scenario_mix records "
                             "'corpus:NAME[:MIX]' next to "
                             "prefix_hit_fraction")
    parser.add_argument("--scenario-repeat", default=None, metavar="MIX",
                        help="scenario arrival mix: 'fixed:K' cycles the "
                             "first K scenarios, 'zipf:S' draws ranks with "
                             "probability 1/(r+1)^S (default: round-robin "
                             "over all scenarios); repeated scenarios are "
                             "what the prefix KV cache accelerates, and "
                             "the report then shows prefix_hit_fraction")
    parser.add_argument("--agents", type=int, default=None, metavar="N",
                        help="expand every scenario to exactly N "
                             "deterministic opinion-holders (base opinions "
                             "cycled as variant-tagged panel members) — the "
                             "AAMAS 50-200 agent regime the utility-matrix "
                             "scoring path is sized for")
    parser.add_argument("--evaluate", action="store_true",
                        help="request per-agent utilities + welfare too")
    parser.add_argument("--timeout-s", type=float, default=None,
                        help="per-request deadline sent to the server")
    parser.add_argument("--client-timeout-s", type=float, default=60.0)
    parser.add_argument("--max-inflight", type=int, default=4,
                        help="(self-contained) worker pool size")
    parser.add_argument("--max-queue-depth", type=int, default=64,
                        help="(self-contained) admission queue bound")
    parser.add_argument("--engine", action=argparse.BooleanOptionalAction,
                        default=True,
                        help="(self-contained) serve through the "
                             "continuous-batching decode engine (the "
                             "default; --no-engine falls back to the "
                             "legacy flush-window batcher)")
    parser.add_argument("--prefix-cache", action="store_true",
                        help="(self-contained) enable the engine's "
                             "cross-request prefix KV cache (implies "
                             "--engine)")
    parser.add_argument("--engine-options", default="{}",
                        help="(self-contained) JSON object of extra "
                             "DecodeEngine options (slots, num_pages, "
                             "prefix_cache_pages, ...)")
    parser.add_argument("--decode-steps", type=int, default=None,
                        metavar="K",
                        help="(self-contained) multi-token decode: the "
                             "engine dispatches K-step on-device decode "
                             "windows per cohort instead of one blocking "
                             "call per token (implies --engine; shorthand "
                             'for --engine-options \'{"decode_steps": K}\')')
    parser.add_argument("--speculative", action="store_true",
                        help="(self-contained) engine-native speculative "
                             "decoding: each decode window drafts K tokens "
                             "per row (n-gram self-draft) and verifies them "
                             "in one dispatch, emitting 1 + accepted real "
                             "tokens (implies --engine; shorthand for "
                             '--engine-options \'{"speculative": true}\'; '
                             "output stays byte-identical)")
    parser.add_argument("--mesh", default=None, metavar="dp=N,tp=M",
                        help="(self-contained) serve over the (data, model) "
                             "device mesh: the decode engine partitions its "
                             "slots + page pools over dp (implies --engine) "
                             "and the report gains dp_shard_slot_occupancy")
    parser.add_argument("--brownout", action="store_true",
                        help="(self-contained) enable the brownout "
                             "controller: overloaded requests run at a "
                             "scaled search budget (degraded 200s) instead "
                             "of timing out")
    parser.add_argument("--target-p95-ms", type=float, default=None,
                        help="(self-contained) latency SLO fed into the "
                             "brownout pressure signal (implies --brownout)")
    parser.add_argument("--metrics-out", default=None,
                        help="write the serve-side registry snapshot delta "
                             "(metrics.json schema) here (self-contained)")
    parser.add_argument("--fleet", type=int, default=1, metavar="N",
                        help="(self-contained) run N backend replicas "
                             "behind the fleet router; the report gains "
                             "replica_request_counts and failover_fraction")
    parser.add_argument("--fleet-options", default="{}",
                        help="(self-contained) JSON object of fleet "
                             "options (tiers, hedge_after_s, elastic, "
                             "autoscale, watchdog_timeout_s, ...)")
    parser.add_argument("--elastic", action="store_true",
                        help="(self-contained, fleet) run the replica "
                             "lifecycle manager: lost replicas respawn "
                             "under their old name with warm PageStore "
                             "prefix pages (shorthand for fleet-options "
                             '{"elastic": true})')
    parser.add_argument("--autoscale", action="store_true",
                        help="(self-contained, fleet) run the "
                             "pressure-driven autoscaler on top of the "
                             "lifecycle manager (implies --elastic)")
    parser.add_argument("--watchdog-timeout-s", type=float, default=None,
                        metavar="S",
                        help="(self-contained, fleet) arm each replica "
                             "engine's hang watchdog: a dispatch wedged "
                             "longer than S marks the replica lost and "
                             "the elastic ladder respawns it")
    parser.add_argument("--chaos-schedule", default=None, metavar="EVENTS",
                        help="(self-contained, fleet) comma-separated "
                             "fault events: 'kill:S' or 'kill:S:NAME' — "
                             "kill a replica S seconds into the run (NAME "
                             "defaults to the first live replica at fire "
                             "time) — or 'restart:S' — roll the whole "
                             "fleet through drain -> capture -> respawn "
                             "-> warm-seed, one replica at a time.  "
                             "Repeated kills exercise the elastic respawn "
                             "path; the report gains a 'chaos' block with "
                             "per-event time-to-recover and the fleet "
                             "respawn count")
    parser.add_argument("--chaos-recover-timeout-s", type=float,
                        default=30.0,
                        help="cap on the per-event recovery poll (fleet "
                             "healthy == size) after a chaos kill")
    parser.add_argument("--kill-replica-at-s", type=float, default=None,
                        metavar="S",
                        help="(self-contained, fleet) legacy single-kill "
                             "form of --chaos-schedule: kill "
                             "--kill-replica S seconds into the run")
    parser.add_argument("--kill-replica", default="r0", metavar="NAME",
                        help="(self-contained, fleet) which replica "
                             "--kill-replica-at-s kills (default: r0)")
    parser.add_argument("--state-dir", default=None, metavar="DIR",
                        help="(self-contained) arm the durable-state "
                             "layer under DIR: fsync'd request WAL + "
                             "idempotency snapshots (single server) and "
                             "the disk-backed PageStore spill tier "
                             "(elastic fleets); the report gains a "
                             "'durability' block")
    parser.add_argument("--telemetry", action="store_true",
                        help="(self-contained) enable the welfare "
                             "telemetry plane (latency + welfare quantile "
                             "sketches, drift detector) on the server")
    parser.add_argument("--slo", action="store_true",
                        help="run the server's burn-rate SLO engine "
                             "(self-contained implies creating it; --url "
                             "mode just reads GET /v1/slo) and print the "
                             "end-of-run SLO verdicts in the report")
    parser.add_argument("--fault-plan", default=None,
                        help="(self-contained) JSON fault plan injected "
                             "below a supervised backend, e.g. "
                             '\'{"seed": 7, "faults": [{"kind": '
                             '"transient_error", "rate": 0.05}]}\'; the '
                             "report gains availability and retried "
                             "fraction")
    parser.add_argument("--transport-fault-plan", default=None,
                        help="(self-contained, fleet) JSON fault plan "
                             "injected into the PageStore transport seam "
                             "(ops ship/fetch/probe; kinds drop, "
                             "duplicate, reorder, bit_flip, partition, "
                             "latency, ...), e.g. '{\"seed\": 7, "
                             '"faults": [{"kind": "drop", "op": "ship", '
                             '"rate": 0.05}, {"kind": "partition", '
                             '"op": "*", "peer": "r1", "after_s": 1.0, '
                             "\"duration_s\": 2.0}]}'; implies elastic "
                             "fleet plumbing and stamps the plan as "
                             "transport_fault_plan provenance in the "
                             "report, next to the seam_degradation "
                             "windows")
    args = parser.parse_args(argv)
    if bool(args.url) == bool(args.self_contained):
        parser.error("exactly one of --url / --self-contained is required")

    from consensus_tpu.serve.loadgen import (
        corpus_requests,
        report_json,
        run_loadgen,
        scenario_requests,
    )

    if args.corpus is not None:
        if args.scenario_repeat is not None:
            parser.error("--corpus and --scenario-repeat are mutually "
                         "exclusive scenario sources")
        name, _, mix = args.corpus.partition(":")
        payloads = corpus_requests(
            name,
            args.requests,
            method=args.method,
            params=json.loads(args.params),
            base_seed=args.seed,
            evaluate=args.evaluate,
            timeout_s=args.timeout_s,
            mix=mix or None,
            agents=args.agents,
        )
    else:
        payloads = scenario_requests(
            args.requests,
            method=args.method,
            params=json.loads(args.params),
            base_seed=args.seed,
            evaluate=args.evaluate,
            timeout_s=args.timeout_s,
            scenario_repeat=args.scenario_repeat,
            agents=args.agents,
        )

    if args.self_contained:
        from consensus_tpu.obs import diff_snapshots, get_registry
        from consensus_tpu.serve import create_server
        from consensus_tpu.utils.io_atomic import atomic_write_json

        engine_options = json.loads(args.engine_options) or {}
        if args.prefix_cache:
            engine_options.setdefault("prefix_cache", True)
        if args.decode_steps is not None:
            engine_options.setdefault("decode_steps", args.decode_steps)
        if args.speculative:
            engine_options.setdefault("speculative", True)
        fleet_options = json.loads(args.fleet_options) or {}
        if args.elastic or args.autoscale:
            fleet_options.setdefault("elastic", True)
        if args.autoscale:
            fleet_options.setdefault("autoscale", True)
        if args.watchdog_timeout_s is not None:
            fleet_options.setdefault(
                "watchdog_timeout_s", args.watchdog_timeout_s)
        if args.transport_fault_plan is not None:
            fleet_options.setdefault(
                "transport_fault_plan", args.transport_fault_plan)
        server = create_server(
            backend="fake",
            port=0,  # ephemeral
            max_inflight=args.max_inflight,
            max_queue_depth=args.max_queue_depth,
            fault_plan=args.fault_plan,
            brownout=args.brownout or args.target_p95_ms is not None,
            target_p95_ms=args.target_p95_ms,
            engine=args.engine or args.prefix_cache or bool(engine_options)
            or args.mesh is not None,
            engine_options=engine_options or None,
            fleet_size=args.fleet,
            fleet_options=fleet_options or None,
            mesh=args.mesh,
            telemetry=args.telemetry,
            slo=args.slo,
            state_dir=args.state_dir,
        ).start()
        schedule = (_parse_chaos_schedule(args.chaos_schedule)
                    if args.chaos_schedule else [])
        if args.kill_replica_at_s is not None:
            schedule.append(
                (args.kill_replica_at_s, "kill", args.kill_replica))
            schedule.sort(key=lambda e: e[0])
        chaos_thread = chaos_stop = None
        chaos_events = []
        if schedule:
            if args.fleet <= 1:
                parser.error("--chaos-schedule / --kill-replica-at-s "
                             "need --fleet > 1")
            import threading

            chaos_stop = threading.Event()
            chaos_thread = threading.Thread(
                target=_run_chaos,
                args=(server.scheduler, schedule,
                      args.chaos_recover_timeout_s, chaos_events,
                      chaos_stop),
                daemon=True,
            )
        before = get_registry().snapshot()
        try:
            if chaos_thread is not None:
                chaos_thread.start()
            report = run_loadgen(
                server.base_url, payloads, args.rate,
                client_timeout_s=args.client_timeout_s,
                include_slo=args.slo,
                transport_fault_plan=args.transport_fault_plan,
            )
            report["device_batches"] = server.scheduler.stats()[
                "device_batches"]
            if chaos_thread is not None:
                # Let in-progress recovery polling settle before reading
                # the event list (bounded; the load has already drained).
                chaos_thread.join(timeout=args.chaos_recover_timeout_s + 5.0)
                recovered = [e["recovered_s"] for e in chaos_events
                             if e["recovered_s"] is not None]
                manager = (server.scheduler.stats().get("fleet") or {}).get(
                    "manager") or {}
                report["chaos"] = {
                    "events": chaos_events,
                    "kills": sum(1 for e in chaos_events
                                 if e["kind"] == "kill"),
                    "rolling_restarts": manager.get("restarts", 0),
                    "recovered": len(recovered),
                    "respawns": manager.get("respawns", 0),
                    "time_to_recover_s": {
                        "max": max(recovered) if recovered else None,
                        "mean": (round(sum(recovered) / len(recovered), 3)
                                 if recovered else None),
                    },
                }
        finally:
            if chaos_stop is not None:
                chaos_stop.set()
            server.stop()
        delta = diff_snapshots(before, get_registry().snapshot())

        def family_total(name):
            family = (delta.get("families") or {}).get(name) or {}
            return sum(s.get("value", 0) for s in family.get("series", []))

        # Retries absorbed below the HTTP surface: supervisor-level call
        # retries plus scheduler-level ticket retries, per offered request.
        retries = family_total("supervisor_retries_total") + family_total(
            "serve_retried_total")
        report["retried_fraction"] = (
            round(retries / args.requests, 4) if args.requests else 0.0)
        if args.metrics_out:
            payload = {"schema": "consensus_tpu.metrics.v1",
                       "metrics": delta}
            atomic_write_json(pathlib.Path(args.metrics_out), payload)
    else:
        report = run_loadgen(
            args.url, payloads, args.rate,
            client_timeout_s=args.client_timeout_s,
            include_slo=args.slo,
            transport_fault_plan=args.transport_fault_plan,
        )

    print(report_json(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
