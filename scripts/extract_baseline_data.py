"""Extract the reference's committed AAMAS artifacts into a bundled dataset.

DATA import (statements + measured welfare numbers, not code) from the
reference's committed result CSVs under /root/reference/results/appendix/ —
the measured quality baseline the TPU build must match (BASELINE.md).

Produces ``consensus_tpu/data/aamas_baseline.json``:

  {"runs": [{
      "name": "aamas_gemma_scenario1_habermas_vs_bon_...",
      "family": "gemma", "scenario": 1, "sweep": "habermas_vs_bon",
      "rows": [{"method", "params": {...}, "seed", "statement",
                "generation_time_s"}, ...],
      "aggregate": [{"method", "params": {...},
                     "egalitarian_welfare_perplexity_mean": {evaluator: x},
                     "egalitarian_welfare_cosine_mean": {evaluator: x},
                     "avg_rank_mean": x|null}, ...]}]}

The A/B parity harness (consensus_tpu/cli/parity_report.py) re-scores these
exact statements with the local backend and reports per-cell deltas against
the bundled aggregates.  Run once from the repo root; the JSON is committed.
"""

from __future__ import annotations

import json
import math
import pathlib
import re
import sys

import pandas as pd

REF = pathlib.Path("/root/reference/results/appendix")
OUT = pathlib.Path(__file__).resolve().parent.parent / (
    "consensus_tpu/data/aamas_baseline.json"
)

RUN_RE = re.compile(r"aamas_(gemma|llama)_scenario(\d)_(.+)_\d{8}_\d{6}")

#: Sweep-identifying params (reference IMPORTANT_PARAMETERS, utils.py:9-16).
PARAM_COLUMNS = [
    "param_n", "param_num_candidates", "param_num_rounds",
    "param_branching_factor", "param_max_depth", "param_beam_width",
]

EVALUATORS = {
    "google_gemma-2-9b-it": "gemma2-9b",
    "meta-llama_Meta-Llama-3.1-8B-Instruct-Turbo": "llama3-8b",
}


def _params(row) -> dict:
    out = {}
    for col in PARAM_COLUMNS:
        value = row.get(col)
        if value is not None and not (isinstance(value, float) and math.isnan(value)):
            out[col.removeprefix("param_")] = (
                int(value) if float(value).is_integer() else float(value)
            )
    return out


def extract_run(run_dir: pathlib.Path) -> dict | None:
    match = RUN_RE.match(run_dir.name)
    if not match:
        return None
    family, scenario, sweep = match.group(1), int(match.group(2)), match.group(3)

    frame = pd.read_csv(run_dir / "results.csv")
    rows = []
    for _, row in frame.iterrows():
        if isinstance(row.get("error_message"), str) and row["error_message"]:
            continue
        statement = row.get("statement")
        if not isinstance(statement, str) or not statement.strip():
            continue
        rows.append(
            {
                "method": row["method"],
                "params": _params(row),
                "seed": int(row["seed"]),
                "statement": statement,
                "generation_time_s": float(row["generation_time_s"]),
            }
        )

    aggregate = []
    agg_file = run_dir / "evaluation/improved_aggregate/aggregated_metrics.csv"
    if agg_file.exists():
        agg = pd.read_csv(agg_file)
        for _, row in agg.iterrows():
            entry = {
                "method": row["method"],
                "params": _params(row),
                "egalitarian_welfare_perplexity_mean": {},
                "egalitarian_welfare_cosine_mean": {},
            }
            for column, model in EVALUATORS.items():
                for metric in (
                    "egalitarian_welfare_perplexity", "egalitarian_welfare_cosine"
                ):
                    value = row.get(f"{column}_{metric}_mean")
                    if value is not None and not math.isnan(value):
                        entry[f"{metric}_mean"][model] = round(float(value), 6)
            rank = row.get("avg_rank_mean")
            entry["avg_rank_mean"] = (
                round(float(rank), 4)
                if rank is not None and not math.isnan(rank)
                else None
            )
            aggregate.append(entry)

    return {
        "name": run_dir.name,
        "family": family,
        "scenario": scenario,
        "sweep": sweep,
        "rows": rows,
        "aggregate": aggregate,
    }


def main() -> None:
    runs = []
    for run_dir in sorted(REF.iterdir()):
        if not run_dir.is_dir():
            continue
        entry = extract_run(run_dir)
        if entry:
            runs.append(entry)
            print(
                f"{run_dir.name}: {len(entry['rows'])} rows, "
                f"{len(entry['aggregate'])} aggregate cells"
            )
    if not runs:
        sys.exit("No runs found — is /root/reference mounted?")
    OUT.write_text(json.dumps({"runs": runs}, indent=1))
    print(f"Wrote {OUT} ({OUT.stat().st_size / 1e6:.2f} MB, {len(runs)} runs)")


if __name__ == "__main__":
    main()
