#!/usr/bin/env python
"""Regenerate the fairness regression goldens under tests/golden/fairness/.

Each golden is one welfare-gap table (see
consensus_tpu/data/scenarios/fairness.py) for one corpus scenario on one
backend.  The fake-backend tables are exact (hash-deterministic); the
tiny-gemma2 tables come from PRNGKey(0) random weights, so they are
deterministic for a fixed jax version and are compared exactly by
tests/test_fairness_regression.py.

Run from the repo root after any intentional change to the corpus, the
prompts, or the score-matrix numerics:

    JAX_PLATFORMS=cpu python scripts/gen_fairness_goldens.py
"""

import argparse
import json
import pathlib
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from consensus_tpu.data.scenarios.fairness import (  # noqa: E402
    BIG_SLATE,
    welfare_gap_table,
)
from consensus_tpu.data.scenarios.registry import (  # noqa: E402
    resolve_scenario_ref,
)

#: Scenarios whose fake-backend tables are pinned.  Chosen so that at
#: least three adversarial families separate all three welfare rules on
#: the mean_prob channel (asserted by the regression suite).
FAKE_SCENARIOS = (
    "polarized-0004",
    "sybil-0006",
    "holdout-0005",
    "contradictory-0003",
    "paraphrase-0004",
    "polarized-500",
)

#: Scenarios pinned on tiny-gemma2 through the FUSED score-matrix path.
#: The 500-agent table doubles as the chunked-under-budget demonstration.
TINY_SCENARIOS = ("polarized-0004", "polarized-500")

FAKE_TABLE_KWARGS = {"n_candidates": 6, "max_tokens": 16, "seed": 0}


def fake_tables():
    from consensus_tpu.backends.fake import FakeBackend

    backend = FakeBackend()
    for sid in FAKE_SCENARIOS:
        scenario = resolve_scenario_ref(f"corpus:v2:{sid}")
        yield f"fake_{sid}", welfare_gap_table(
            backend, scenario, **FAKE_TABLE_KWARGS)


def tiny_tables():
    from consensus_tpu.backends.tpu import TPUBackend

    # max_context must cover the agent-prompt prefixes (~670 tokens under
    # the near-char-level tiny tokenizer) or the fused gate falls back.
    backend = TPUBackend(model="tiny-gemma2", dtype="float32",
                         max_context=1024)
    for sid in TINY_SCENARIOS:
        scenario = resolve_scenario_ref(f"corpus:v2:{sid}")
        before = backend.matrix_stats["chunks"]
        table = welfare_gap_table(backend, scenario, candidates=BIG_SLATE)
        table["matrix_chunks"] = backend.matrix_stats["chunks"] - before
        yield f"tiny-gemma2_{sid}", table


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--out", default=str(REPO_ROOT / "tests" / "golden" / "fairness"))
    parser.add_argument(
        "--skip-tiny", action="store_true",
        help="only regenerate the fake-backend tables")
    args = parser.parse_args()

    out = pathlib.Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    sources = [fake_tables()]
    if not args.skip_tiny:
        sources.append(tiny_tables())
    for source in sources:
        for name, table in source:
            path = out / f"{name}.json"
            path.write_text(json.dumps(table, indent=2, sort_keys=True)
                            + "\n")
            prob = table["channels"]["mean_prob"]
            print(f"{name}: path={table['matrix_path']} "
                  f"winners={prob['winners']} "
                  f"separated={prob['rules_separated']}")


if __name__ == "__main__":
    main()
