"""Int8-vs-bf16 end-to-end welfare delta (VERDICT r2 #7).

Weight-only int8 is the production default (it is the only way 8-9B models
fit one v5e chip), but round 2 shipped it with no measurement of what it
does to the WELFARE METRICS the paper reports.  This script scores the
reference's own committed AAMAS statements (the parity harness's fixed
inputs, so generation randomness is out of the loop) through the SAME
model weights twice — bf16 and int8-quantized — and reports the per-cell
egalitarian-perplexity delta.  The weights are random (no checkpoint on
the box), but quantization noise is a property of the numeric path, not
of the weight values' provenance; the delta table bounds the metric cost
of the production default.

Usage: PYTHONPATH=. python scripts/int8_delta_report.py [--model gemma2-2b]
       [--scenario 1] [--quick]   (repo root; needs the chip unless --quick)
"""

from __future__ import annotations

import argparse
import json
import pathlib
from datetime import datetime

import numpy as np

from consensus_tpu.backends.tpu import TPUBackend
from consensus_tpu.cli.parity_report import build_report


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="gemma2-2b")
    parser.add_argument("--scenario", nargs="*", type=int, default=[1])
    parser.add_argument("--sweep", nargs="*", default=["habermas_vs_bon"])
    parser.add_argument("--quick", action="store_true", help="tiny model, CPU-ok")
    args = parser.parse_args()

    model = "tiny-gemma2" if args.quick else args.model
    common = dict(
        model=model,
        max_context=1024,
        base_seed=0,
        use_flash_attention=not args.quick,
        max_batch_rows=32,
        shared_context_scoring=True,
    )
    reports = {}
    for mode in ("bf16", "int8"):
        backend = TPUBackend(
            quantization=None if mode == "bf16" else "int8", **common
        )
        reports[mode] = build_report(
            backend,
            scenarios=args.scenario,
            sweeps=args.sweep,
            weights="random (identical across modes: same base_seed)",
        )
        del backend

    rows = []
    for bf16_cell, int8_cell in zip(
        reports["bf16"]["cells"], reports["int8"]["cells"]
    ):
        assert bf16_cell["method"] == int8_cell["method"]
        assert bf16_cell["params"] == int8_cell["params"]
        bf16_ppl = bf16_cell["local_egalitarian_perplexity"]
        int8_ppl = int8_cell["local_egalitarian_perplexity"]
        rows.append(
            {
                "scenario": bf16_cell["scenario"],
                "method": bf16_cell["method"],
                "params": bf16_cell["params"],
                "egal_ppl_bf16": bf16_ppl,
                "egal_ppl_int8": int8_ppl,
                "delta_pct": round(100.0 * (int8_ppl - bf16_ppl) / bf16_ppl, 3),
            }
        )

    deltas = [abs(r["delta_pct"]) for r in rows]
    payload = {
        "generated": datetime.now().isoformat(timespec="seconds"),
        "model": model,
        "weights": "random (same base_seed both modes; fixed reference statements)",
        "n_cells": len(rows),
        "mean_abs_delta_pct": round(float(np.mean(deltas)), 3) if deltas else None,
        "max_abs_delta_pct": round(float(np.max(deltas)), 3) if deltas else None,
        "cells": rows,
    }
    out = pathlib.Path("reports")
    out.mkdir(exist_ok=True)
    (out / "int8_delta.json").write_text(json.dumps(payload, indent=2))

    lines = [
        "# Int8-vs-bf16 welfare delta (production quantization default)",
        "",
        f"- Generated: {payload['generated']}  |  model: {model}",
        "- Inputs: the reference's committed AAMAS statements (fixed), scored",
        "  by the SAME random weights in bf16 and int8 — the delta isolates",
        "  the quantization noise of the metric path.",
        f"- Cells: {payload['n_cells']}  |  mean |Δ egal-ppl|: "
        f"{payload['mean_abs_delta_pct']}%  |  max: {payload['max_abs_delta_pct']}%",
        "",
        "| scenario | method | params | egal ppl bf16 | egal ppl int8 | Δ% |",
        "|---|---|---|---|---|---|",
    ]
    for row in rows:
        params = ", ".join(f"{k}={v}" for k, v in row["params"].items())
        lines.append(
            f"| {row['scenario']} | {row['method']} | {params} "
            f"| {row['egal_ppl_bf16']} | {row['egal_ppl_int8']} "
            f"| {row['delta_pct']} |"
        )
    (out / "int8_delta.md").write_text("\n".join(lines) + "\n")
    print(
        json.dumps(
            {k: payload[k] for k in ("n_cells", "mean_abs_delta_pct", "max_abs_delta_pct")}
        )
    )


if __name__ == "__main__":
    main()
