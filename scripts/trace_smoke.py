"""CI smoke: watchdog trip -> flight-recorder blackbox dump.

Arms a fake-backend serve stack with a hang fault on the first generate
(the engine loop wedges inside the device call), lets the engine hang
watchdog trip, and asserts the crash forensics the ISSUE-14 flight
recorder promises:

* the watchdog trip writes a parseable ``blackbox.json`` (atomic, schema
  ``consensus_tpu.blackbox.v1``) whose ``reason`` is ``watchdog_trip``
  and whose event ring holds the trip itself;
* the trip is visible to operators in ``GET /healthz`` (``engine.
  watchdog.wedged``).

Exit 0 on success, 1 with a reason on any failed check.  Stdlib-only
client, fake backend — no device, no network beyond loopback.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _wait_for(predicate, timeout_s=10.0):
    deadline = time.perf_counter() + timeout_s
    while time.perf_counter() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return predicate()


def main() -> int:
    from consensus_tpu.backends.fake import FakeBackend
    from consensus_tpu.backends.faults import (
        FaultInjectingBackend,
        FaultPlan,
        FaultSpec,
    )
    from consensus_tpu.obs.trace import get_flight_recorder
    from consensus_tpu.serve.http_frontend import ConsensusServer
    from consensus_tpu.serve.scheduler import RequestScheduler
    from consensus_tpu.serve.service import ConsensusService

    blackbox_path = os.path.join(
        tempfile.mkdtemp(prefix="trace_smoke_"), "blackbox.json")
    recorder = get_flight_recorder()
    recorder.configure(blackbox_path)

    plan = FaultPlan(seed=1, faults=[
        FaultSpec(kind="hang", op="generate", call_index=0)])
    faulty = FaultInjectingBackend(FakeBackend(), plan)
    service = ConsensusService(faulty)
    scheduler = RequestScheduler(
        handler=service.run,
        backend=faulty,
        engine=True,
        engine_options={"watchdog_timeout_s": 0.4},
        default_timeout_s=30.0,
    )
    engine = scheduler.batching.engine
    server = ConsensusServer(scheduler, port=0).start()
    try:
        payload = json.dumps({
            "issue": "Should the town build a new library?",
            "agent_opinions": {"A": "Yes, knowledge matters.",
                               "B": "Only if the budget allows."},
            "method": "best_of_n",
            "params": {"n": 2, "max_tokens": 8},
            "seed": 7,
        }).encode("utf-8")

        def fire():
            request = urllib.request.Request(
                server.base_url + "/v1/consensus", data=payload,
                headers={"Content-Type": "application/json"}, method="POST",
            )
            try:
                urllib.request.urlopen(request, timeout=30.0).read()
            except Exception:
                pass  # the wedged request is expected to fail

        threading.Thread(target=fire, daemon=True).start()

        if not _wait_for(lambda: faulty.hangs_active >= 1):
            print("FAIL: hang fault never armed", file=sys.stderr)
            return 1
        if not _wait_for(lambda: engine.watchdog_trips >= 1):
            print("FAIL: watchdog never tripped", file=sys.stderr)
            return 1

        with urllib.request.urlopen(
            server.base_url + "/healthz", timeout=5.0
        ) as response:
            health = json.loads(response.read().decode("utf-8"))
        watchdog = (health.get("engine") or {}).get("watchdog") or {}
        if not (watchdog.get("enabled") and watchdog.get("wedged")):
            print(f"FAIL: /healthz watchdog not wedged: {watchdog}",
                  file=sys.stderr)
            return 1

        if not _wait_for(lambda: os.path.exists(blackbox_path)):
            print("FAIL: blackbox.json never written", file=sys.stderr)
            return 1
        with open(blackbox_path, encoding="utf-8") as handle:
            blackbox = json.load(handle)
        if blackbox.get("schema") != "consensus_tpu.blackbox.v1":
            print(f"FAIL: bad blackbox schema: {blackbox.get('schema')}",
                  file=sys.stderr)
            return 1
        if blackbox.get("reason") != "watchdog_trip":
            print(f"FAIL: bad dump reason: {blackbox.get('reason')}",
                  file=sys.stderr)
            return 1
        kinds = [e.get("kind") for e in blackbox.get("events", [])]
        if "watchdog_trip" not in kinds:
            print(f"FAIL: no watchdog_trip event in ring: {kinds}",
                  file=sys.stderr)
            return 1

        print(json.dumps({
            "trace_smoke": "ok",
            "blackbox": blackbox_path,
            "reason": blackbox["reason"],
            "events": len(blackbox.get("events", [])),
            "iterations": len(blackbox.get("iterations", [])),
            "watchdog_trips": engine.watchdog_trips,
        }))
        return 0
    finally:
        faulty.release_hangs()
        server.stop(drain=False)
        recorder.configure(None)


if __name__ == "__main__":
    raise SystemExit(main())
