"""Scoring-phase microbench: where does teacher-forced scoring lose its 10x?

VERDICT r3 #3: scoring is prefill-shaped and should run at 30-50% MFU, but
the sweep's combined cells clock ~0.35-0.5 s per 1k scored tokens (~5% of
v5e bf16 peak).  This script times the two production scorers warm at
sweep shapes and splits model-forward cost from the streamed-logsumexp
cost (the vocab projection sweeps the full 256k x 2304 head per call):

- token_logprobs_streamed (classic: B rows x S columns)
- shared_context_token_logprobs (shared: 1 ctx row + P x L continuations)
- forward-only arms (return_hidden, no head sweep) isolate the logsumexp.

Prints achieved TFLOP/s against the model-forward FLOPs (2 * params *
tokens) and against total useful FLOPs (incl. the head sweep), so the
padding/compute split is explicit.

Usage: PYTHONPATH=/root/.axon_site:/root/repo python scripts/scoring_bench.py
"""

from __future__ import annotations

import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from consensus_tpu.models.config import get_model_config
from consensus_tpu.models.quant import quantize_params
from consensus_tpu.models.transformer import (
    forward,
    init_params,
    shared_context_token_logprobs,
    token_logprobs_streamed,
)

from consensus_tpu.utils.mfu import V5E_BF16_PEAK_TFLOPS as PEAK_TFLOPS  # noqa: E402
from consensus_tpu.utils.mfu import param_count  # noqa: E402

MODEL = "gemma2-2b"


def bench(label, fn, flops_model=0.0, flops_total=0.0, repeats=3):
    out = fn()
    np.asarray(out[0] if isinstance(out, tuple) else out)  # warm compile
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn()
        np.asarray(out[0] if isinstance(out, tuple) else out)  # force tunnel
        best = min(best, time.perf_counter() - t0)
    mfu_m = flops_model / best / 1e12 / PEAK_TFLOPS * 100 if flops_model else 0
    mfu_t = flops_total / best / 1e12 / PEAK_TFLOPS * 100 if flops_total else 0
    print(
        f"{label:52s} {best:7.3f}s  model-MFU {mfu_m:5.1f}%  "
        f"total-MFU {mfu_t:5.1f}%"
    )
    return best


def main() -> None:
    config = get_model_config(MODEL)
    params = quantize_params(init_params(config, jax.random.PRNGKey(0), jnp.bfloat16))
    import dataclasses

    config = dataclasses.replace(config, use_flash_attention=True)
    n_params = param_count(config)
    # 2*n_params includes the head matmul ONCE (utils/mfu.py convention) —
    # split it out so the forward-only arms (no head sweep) are credited
    # only the body FLOPs and the streamed arms don't double-count it.
    head_flops_per_slot = 2 * config.d_model * config.vocab_size
    body_flops_per_slot = 2 * n_params - head_flops_per_slot

    key = jax.random.PRNGKey(1)

    def classic_arm(batch, width):
        tokens = jax.random.randint(key, (batch, width), 1, 255, jnp.int32)
        valid = jnp.ones((batch, width), bool)
        slots = batch * width
        fwd = body_flops_per_slot * slots
        tot = 2 * n_params * slots
        bench(
            f"classic streamed B={batch} S={width}",
            lambda: token_logprobs_streamed(params, config, tokens, valid),
            flops_model=fwd, flops_total=tot,
        )
        bench(
            f"classic forward-only B={batch} S={width}",
            lambda: forward(
                params, config, tokens,
                jnp.maximum(jnp.cumsum(valid.astype(jnp.int32), axis=1) - 1, 0),
                valid, return_hidden=True,
            )[0],
            flops_model=fwd, flops_total=fwd,
        )

    def shared_arm(p, l, ctx):
        ctx_tokens = jax.random.randint(key, (1, ctx), 1, 255, jnp.int32)
        ctx_valid = jnp.ones((1, ctx), bool)
        cont = jax.random.randint(key, (p, l), 1, 255, jnp.int32)
        cont_valid = jnp.ones((p, l), bool)
        slots = p * l
        fwd = body_flops_per_slot * (slots + ctx)
        tot = fwd + head_flops_per_slot * slots
        bench(
            f"shared-context P={p} L={l} ctx={ctx}",
            lambda: shared_context_token_logprobs(
                params, config, ctx_tokens, ctx_valid, cont, cont_valid
            ),
            flops_model=fwd, flops_total=tot,
        )

    arms = os.environ.get("BENCH_ARMS", "all")
    if arms in ("all", "classic"):
        classic_arm(32, 1024)
        classic_arm(32, 384)
        classic_arm(64, 384)
    if arms in ("all", "shared"):
        shared_arm(32, 192, 1024)
        shared_arm(64, 192, 1024)
        shared_arm(32, 64, 1024)


if __name__ == "__main__":
    main()
